"""Schedule compiler tests: topology -> ppermute rounds."""
import numpy as np
import pytest

from bluefog_tpu import schedule as sch
from bluefog_tpu import topology as tu


def _check_rounds_are_partial_permutations(rounds):
    for rnd in rounds:
        srcs = [s for s, _ in rnd]
        dsts = [d for _, d in rnd]
        assert len(set(srcs)) == len(srcs)
        assert len(set(dsts)) == len(dsts)


def test_circulant_decomposition_is_optimal():
    """Exp2(8) has out-degree 3 -> exactly 3 full-permutation rounds."""
    sched = sch.compile_topology(tu.ExponentialTwoGraph(8))
    assert sched.num_rounds == 3
    for rnd in sched.rounds:
        assert len(rnd) == 8  # full permutation: every device sends
    _check_rounds_are_partial_permutations(sched.rounds)


def test_ring_one_round_per_direction():
    sched = sch.compile_topology(tu.RingGraph(8, connect_style=2))
    assert sched.num_rounds == 1
    assert set(sched.rounds[0]) == {(i, (i + 1) % 8) for i in range(8)}


def test_star_coloring_valid():
    sched = sch.compile_topology(tu.StarGraph(8))
    _check_rounds_are_partial_permutations(sched.rounds)
    # center sends to 7 leaves -> at least 7 rounds; every edge appears once
    all_edges = [e for rnd in sched.rounds for e in rnd]
    assert len(all_edges) == len(set(all_edges)) == 14


def test_tables_match_topology_weights():
    topo = tu.RingGraph(8, connect_style=0)
    sched = sch.compile_topology(topo, weighted=True)
    # Effective combine at rank 3: self*1/3 + left*1/3 + right*1/3
    sw, nbr = tu.GetRecvWeights(topo, 3)
    assert sched.self_weight[3] == pytest.approx(sw)
    got = {}
    for r in range(sched.num_rounds):
        src = sched.recv_src[r, 3]
        if src >= 0:
            got[int(src)] = got.get(int(src), 0.0) + float(sched.recv_weight[r, 3])
    assert got == pytest.approx(nbr)


def test_unweighted_uniform():
    topo = tu.ExponentialTwoGraph(8)
    sched = sch.compile_topology(topo, weighted=False)
    np.testing.assert_allclose(sched.self_weight, np.full(8, 0.25))
    assert sched.recv_weight[sched.recv_weight != 0] == pytest.approx(0.25)


def test_compile_from_weights_rejects_mismatched_edges():
    with pytest.raises(ValueError):
        sch.compile_from_weights(
            4,
            self_weights=[0.5] * 4,
            src_weights_per_rank=[{1: 0.5}, {2: 0.5}, {3: 0.5}, {0: 0.5}],
            dst_weights_per_rank=[{2: 1.0}, {2: 1.0}, {3: 1.0}, {0: 1.0}],
        )


def test_dynamic_compile_one_ppermute_per_step():
    topo = tu.ExponentialTwoGraph(8)
    factory = lambda r: tu.GetDynamicOnePeerSendRecvRanks(topo, r)
    scheds = sch.compile_dynamic_schedules(factory, 8)
    assert len(scheds) == 3  # period = out-degree of Exp2(8)
    for s in scheds:
        assert s.num_rounds == 1
        assert len(s.rounds[0]) == 8


def test_dynamic_weights_uniform_over_recv():
    topo = tu.ExponentialTwoGraph(8)
    factory = lambda r: tu.GetDynamicOnePeerSendRecvRanks(topo, r)
    s0 = sch.compile_dynamic_schedules(factory, 8)[0]
    # one-peer: every rank receives exactly one value -> weights 1/2
    np.testing.assert_allclose(s0.self_weight, np.full(8, 0.5))
    np.testing.assert_allclose(s0.recv_weight[0], np.full(8, 0.5))


def test_schedule_hash_stable():
    a = sch.compile_topology(tu.ExponentialTwoGraph(8))
    b = sch.compile_topology(tu.ExponentialTwoGraph(8))
    c = sch.compile_topology(tu.RingGraph(8))
    assert a == b and hash(a) == hash(b)
    assert a != c


def test_random_digraph_coloring_properties():
    """Arbitrary digraphs: every round is a partial permutation, every edge
    appears exactly once, and rounds <= 2*max_degree - 1 (greedy interval
    bound)."""
    rng = np.random.default_rng(0)
    for trial in range(20):
        n = int(rng.integers(2, 24))
        density = rng.uniform(0.05, 0.6)
        edges = [(int(u), int(v)) for u in range(n) for v in range(n)
                 if u != v and rng.random() < density]
        if not edges:
            continue
        rounds = sch.color_edges(edges, n)
        flat = [e for r in rounds for e in r]
        assert sorted(flat) == sorted(set(edges))
        for r in rounds:
            assert len({e[0] for e in r}) == len(r)     # distinct senders
            assert len({e[1] for e in r}) == len(r)     # distinct receivers
        out_deg = np.zeros(n, int); in_deg = np.zeros(n, int)
        for u, v in set(edges):
            out_deg[u] += 1; in_deg[v] += 1
        max_deg = max(out_deg.max(), in_deg.max())
        assert len(rounds) <= 2 * max_deg - 1


@pytest.mark.parametrize("n", [1, 2, 3, 5, 6, 7, 12])
def test_compile_topology_odd_sizes(n):
    """Generators + compiler handle non-power-of-2 and tiny sizes."""
    for make in (tu.ExponentialTwoGraph, tu.RingGraph, tu.FullyConnectedGraph):
        if n == 1:
            continue
        topo = make(n)
        s = sch.compile_topology(topo, weighted=True)
        W = tu.to_weight_matrix(topo)
        # reconstruct the mixing matrix from the compiled tables
        M = np.zeros((n, n))
        for dst in range(n):
            M[dst, dst] = s.self_weight[dst]
        for r in range(s.num_rounds):
            for dst in range(n):
                src = s.recv_src[r, dst]
                if src >= 0:
                    M[src, dst] += s.recv_weight[r, dst]
        np.testing.assert_allclose(M, W, atol=1e-6)


def test_compile_topology_size_one():
    topo = tu.FullyConnectedGraph(1)
    s = sch.compile_topology(topo, weighted=True)
    assert s.num_rounds == 0 and s.self_weight[0] == 1.0


# ---------------------------------------------------------------------------
# Column-stochasticity witness (the column counterpart of rounds_edge_disjoint)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("gen", [
    tu.ExponentialTwoGraph, tu.RingGraph, tu.MeshGrid2DGraph, tu.StarGraph,
    tu.FullyConnectedGraph,
])
@pytest.mark.parametrize("weighted", [True, False])
def test_columns_stochastic_static(gen, weighted):
    """Every compiled static schedule keeps each receiver's mass at 1."""
    sched = sch.compile_topology(gen(8), weighted=weighted)
    assert sch.columns_stochastic(sched)


@pytest.mark.parametrize("intra,inter", [("dense", "exp2"), ("exp2", "ring")])
def test_columns_stochastic_two_level(intra, inter):
    """The composed two-level schedule keeps columns stochastic."""
    sched = sch.compile_topology(
        tu.TwoLevelGraph(4, 2, intra=intra, inter=inter), weighted=True)
    assert sch.columns_stochastic(sched)
    assert sch.rounds_edge_disjoint(sched)


def test_columns_stochastic_dynamic_period():
    """Every schedule of a compiled dynamic period passes the witness."""
    topo = tu.ExponentialTwoGraph(8)
    scheds = sch.compile_dynamic_schedules(
        lambda r: tu.GetDynamicOnePeerSendRecvRanks(topo, r), 8)
    assert scheds and all(sch.columns_stochastic(s) for s in scheds)


def test_columns_stochastic_detects_mass_leak():
    """A hand-built schedule that drops received mass fails the witness."""
    bad = sch.compile_from_weights(
        size=4,
        self_weights=[0.5] * 4,
        src_weights_per_rank=[{(r + 1) % 4: 0.25} for r in range(4)],
    )
    assert not sch.columns_stochastic(bad)
    good = sch.compile_from_weights(
        size=4,
        self_weights=[0.5] * 4,
        src_weights_per_rank=[{(r + 1) % 4: 0.5} for r in range(4)],
    )
    assert sch.columns_stochastic(good)


def test_columns_stochastic_respects_send_scales():
    """Dst-weighted schedules count the sender-side scale in arriving mass."""
    # each rank receives from r+1 with recv weight 0.5 but the sender
    # pre-scales by 0.5 -> only 0.25 arrives: not column-stochastic
    scaled = sch.compile_from_weights(
        size=4,
        self_weights=[0.5] * 4,
        src_weights_per_rank=[{(r + 1) % 4: 0.5} for r in range(4)],
        dst_weights_per_rank=[{(r - 1) % 4: 0.5} for r in range(4)],
    )
    assert scaled.uses_dst_weighting
    assert not sch.columns_stochastic(scaled)


# ---------------------------------------------------------------------------
# dynamic_schedule_period: hash-based scan equivalence
# ---------------------------------------------------------------------------

def _brute_force_period(generator_factory, size, probe=256):
    """The pre-optimization reference implementation: per-candidate rescan
    of every rank's raw yield tuples (O(size * probe^2))."""
    seqs = []
    for rank in range(size):
        gen = generator_factory(rank)
        seqs.append([next(gen) for _ in range(probe)])
    for period in range(1, probe // 2 + 1):
        if all(seqs[r][t] == seqs[r][t % period]
               for r in range(size) for t in range(probe)):
            return period
    raise ValueError("no period")


@pytest.mark.parametrize("name,size,factory", [
    ("one-peer-exp2", 16,
     lambda: (lambda r: tu.GetDynamicOnePeerSendRecvRanks(
         tu.ExponentialTwoGraph(16), r))),
    ("one-peer-ring", 12,
     lambda: (lambda r: tu.GetDynamicOnePeerSendRecvRanks(
         tu.RingGraph(12), r))),
    ("machine-exp2", 8,
     lambda: (lambda r: tu.GetExp2DynamicSendRecvMachineRanks(16, 2, 2 * r, 0))),
    ("inner-outer-ring", 16,
     lambda: (lambda r: tu.GetInnerOuterRingDynamicSendRecvRanks(16, 4, r))),
    ("inner-outer-exp2", 16,
     lambda: (lambda r: tu.GetInnerOuterExpo2DynamicSendRecvRanks(16, 4, r))),
])
def test_dynamic_schedule_period_equivalence(name, size, factory):
    """The hashed scan returns exactly what the brute-force scan returned.

    Timing-insensitive by design: equivalence of the *result*, for every
    shipped generator family, is the regression contract — plus the period
    property itself (signatures repeat at the detected period and at no
    shorter candidate)."""
    probe = 64
    got = sch.dynamic_schedule_period(factory(), size, probe=probe)
    want = _brute_force_period(factory(), size, probe=probe)
    assert got == want, name

    gens = [factory()(r) for r in range(size)]
    sigs = [tuple((tuple(s), tuple(rv)) for s, rv in
                  (next(g) for g in gens)) for _ in range(probe)]
    assert all(sigs[t] == sigs[t % got] for t in range(probe))
    for shorter in range(1, got):
        assert not all(sigs[t] == sigs[t % shorter] for t in range(probe))


def test_dynamic_schedule_period_no_period_raises():
    """An aperiodic family still fails loudly, like before."""
    def factory(rank):
        def gen():
            t = 0
            while True:
                # the recv id grows without bound: no candidate period fits
                yield ([(rank + 1) % 8], [rank + 8 * t])
                t += 1
        return gen()
    with pytest.raises(ValueError, match="no period"):
        sch.dynamic_schedule_period(factory, 8, probe=16)
