"""bluefog_tpu.serve: the decentralized inference engine.

What is pinned here:

* **engine correctness** — greedy decode through the bucketed
  prefill+decode engine (gossip-DP axis = replica axis, PP ppermute
  cycle, TP psum, slotted KV cache) matches an independent per-tp-rank
  numpy dense reference token-for-token, on both replicas, with mixed
  prompt lengths and batch buckets;
* **zero retraces** — after ``warmup()`` every served shape hits a
  declared bucket; the retrace sentinel stays 0 across the whole battery;
* **KV slot reuse** — a slot that served one request and was evicted
  produces bit-identical output for the next request (stale rows are
  masked, never read);
* **the float64 decode oracle** — ``RingTransformerLM``'s cached decode
  path (``cache=``/``init_decode_cache``) is logit-identical to the full
  forward at float64, including grouped-query attention and rope;
* **the train→serve estate** — 8 virtual ranks: 2 training replicas
  (pp=2) gossiping while 2 serving replicas answer 16 concurrent
  requests, with :class:`WeightRefresher` pulling fresh params
  mid-traffic (staleness gauge rises with train steps, drops to 0 on
  pull) and KV donation intact;
* **the chaos drill** — a serving replica killed mid-stream: survivors
  complete their requests, the refresher pulls through the healed
  topology, and the flight bundle + postmortem blame the right rank
  (the ``serve`` block carries the last-request ids);
* **serving checkpoints** — ``save_for_serving``/``load_for_serving``
  round-trip params-only snapshots, reject training state, skip torn
  directories;
* **the launcher surface** — ``bfrun-tpu --serve`` env plumbing and the
  no-command default to ``python -m bluefog_tpu.serve``.
"""
import importlib.util
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from bluefog_tpu import checkpoint
from bluefog_tpu.parallel import compose
from bluefog_tpu.serve import (Scheduler, ServeConfig, ServeEngine,
                               SlotAllocator, WeightRefresher)
from bluefog_tpu.serve.engine import _parse_buckets
from bluefog_tpu.serve.kv_cache import KVCacheConfig, attend_rows, init_cache
from bluefog_tpu.utils import chaos as bfchaos
from bluefog_tpu.utils import flight as bfflight
from bluefog_tpu.utils import metrics as bfm

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(autouse=True)
def _clean():
    bfm.reset_metrics()
    bfchaos.uninstall()
    bfflight.reset()
    yield
    bfchaos.uninstall()
    bfflight.reset()
    bfm.reset_metrics()


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name.replace("/", "_") + "_mod", os.path.join(REPO, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Config + allocator units
# ---------------------------------------------------------------------------

def test_parse_buckets():
    assert _parse_buckets("1,2,4@8,16") == ((1, 2, 4), (8, 16))
    assert _parse_buckets("1,8") == ((1, 8), ())
    with pytest.raises(ValueError, match="expected"):
        _parse_buckets("a,b@c")


def test_serve_config_validation():
    with pytest.raises(ValueError, match="ascending"):
        ServeConfig(batch_buckets=(4, 2))
    with pytest.raises(ValueError, match="resident slot"):
        ServeConfig(batch_buckets=(1, 16), slots=8)
    with pytest.raises(ValueError, match="exceeds max_len"):
        ServeConfig(prefill_buckets=(8, 128), max_len=64)
    with pytest.raises(ValueError, match="at least one"):
        ServeConfig(batch_buckets=())
    cfg = ServeConfig()
    assert cfg.batch_bucket_for(3) == 4
    assert cfg.prefill_bucket_for(9) == 16
    with pytest.raises(ValueError, match="exceed"):
        cfg.batch_bucket_for(99)


def test_serve_config_from_env(monkeypatch):
    monkeypatch.setenv("BLUEFOG_SERVE_BUCKETS", "1,2@4,32")
    cfg = ServeConfig.from_env(slots=4)
    assert cfg.batch_buckets == (1, 2)
    assert cfg.prefill_buckets == (4, 32)
    assert cfg.slots == 4


def test_slot_allocator_and_gauges():
    a = SlotAllocator(3, replica=1)
    assert [a.alloc(), a.alloc(), a.alloc()] == [0, 1, 2]
    assert a.alloc() is None
    a.free(1)
    assert a.alloc() == 1                       # lowest-free-first
    with pytest.raises(ValueError):
        a.free(7)
    assert a.in_use == 3 and a.occupancy == 1.0
    g = bfm.get_metric("bluefog_serve_kv_slots_in_use")
    assert g is not None and g.value(replica=1) == 3.0


def test_attend_rows_matches_dense_gqa():
    """attend_rows (gather + GQA repeat + masked softmax) == a numpy dense
    reference over the valid prefix, garbage rows masked out."""
    rng = np.random.default_rng(0)
    S, L, H, Hkv, Dh = 3, 8, 4, 2, 6
    kl = rng.normal(size=(5, Hkv, L, Dh)).astype(np.float32)
    vl = rng.normal(size=(5, Hkv, L, Dh)).astype(np.float32)
    q = rng.normal(size=(S, H, Dh)).astype(np.float32)
    slots = np.array([4, 0, 2], np.int32)
    lens = np.array([3, 7, 1], np.int32)        # attend over rows 0..lens
    out = np.asarray(attend_rows(q, kl, vl, slots, lens))
    for i in range(S):
        n = lens[i] + 1
        # [n, Hkv, Dh] -> repeat to [n, H, Dh] for the dense reference
        k = np.repeat(kl[slots[i], :, :n].transpose(1, 0, 2),
                      H // Hkv, axis=1)
        v = np.repeat(vl[slots[i], :, :n].transpose(1, 0, 2),
                      H // Hkv, axis=1)
        s = np.einsum("hd,lhd->hl", q[i] * Dh ** -0.5, k)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("hl,lhd->hd", p, v)
        np.testing.assert_allclose(out[i], want, rtol=2e-5, atol=2e-6)


def test_kv_cache_shapes():
    cfg = KVCacheConfig(layers=2, slots=4, max_len=8, kv_heads=2, head_dim=4)
    c = init_cache(cfg)
    assert c["k"].shape == (2, 5, 2, 8, 4)      # slots + 1 trash row
    assert cfg.trash_slot == 4
    assert cfg.bytes() == 2 * 2 * 5 * 8 * 2 * 4 * 4


# ---------------------------------------------------------------------------
# The engine vs a per-tp-rank dense numpy reference (dp=2 x pp=2 x tp=2)
# ---------------------------------------------------------------------------

_CFG = dict(vocab=32, d_model=32, heads=4, layers=4, seq_len=32)


@pytest.fixture(scope="module")
def engine(cpu_devices):
    cfg = compose.LMConfig(**_CFG)
    m = compose.compose_parallelism(2, 2, 2, 1, devices=cpu_devices)
    params = compose.init_lm_params(cfg, m, seed=3)
    scfg = ServeConfig(batch_buckets=(1, 2), prefill_buckets=(4, 8),
                       slots=4, max_len=32, decode_steps_per_call=1)
    eng = ServeEngine(m, cfg, params, scfg)
    eng.warmup()
    return eng


def _ref_greedy(eng, prompt, steps):
    """Greedy decode via plain numpy: per-tp-rank matmuls summed, dense
    causal attention, full forward re-run per token."""
    m, cfg = eng.m, eng.cfg
    P = jax.tree.map(np.asarray, eng.params)
    Lps = cfg.layers // m.pp
    H, D = cfg.heads, cfg.d_model
    Hl, hsz = H // m.tp, D // H

    def dev(stage, t):
        return (stage * m.tp + t) * m.sp        # replica 0's shard row

    def rope(x, pos):
        half = x.shape[-1] // 2
        freqs = 10000.0 ** (-np.arange(half) / half)
        ang = pos[:, None] * freqs[None]
        cos, sin = np.cos(ang)[:, None, :], np.sin(ang)[:, None, :]
        x1, x2 = x[..., :half], x[..., half:]
        return np.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)

    def ln(z):
        mu = z.mean(-1, keepdims=True)
        return (z - mu) / np.sqrt(z.var(-1, keepdims=True) + 1e-6)

    def forward(toks):
        T = len(toks)
        pos = np.arange(T)
        x = P["shared"]["embed"][0][toks]
        for l in range(cfg.layers):
            st, li = l // Lps, l % Lps
            h = ln(x)
            delta = np.zeros_like(x)
            for t in range(m.tp):
                d = dev(st, t)
                qkv = h @ P["blocks"]["wqkv"][d][li]
                q, k, v = np.split(qkv, 3, -1)
                q = rope(q.reshape(T, Hl, hsz), pos)
                k = rope(k.reshape(T, Hl, hsz), pos)
                v = v.reshape(T, Hl, hsz)
                s = np.einsum("ihd,jhd->ihj", q * hsz ** -0.5, k)
                mask = pos[:, None] >= pos[None, :]
                s = np.where(mask[:, None, :], s, -np.inf)
                p = np.exp(s - s.max(-1, keepdims=True))
                p = p / p.sum(-1, keepdims=True)
                att = np.einsum("ihj,jhd->ihd", p, v).reshape(T, Hl * hsz)
                delta += att @ P["blocks"]["wo"][d][li]
            x = x + delta
            h = ln(x)
            delta = np.zeros_like(x)
            for t in range(m.tp):
                d = dev(st, t)
                g = h @ P["blocks"]["w1"][d][li]
                g = 0.5 * g * (1 + np.tanh(
                    np.sqrt(2 / np.pi) * (g + 0.044715 * g ** 3)))
                delta += g @ P["blocks"]["w2"][d][li]
            x = x + delta
        return ln(x) @ P["shared"]["head"][0]

    toks, out = list(prompt), []
    for _ in range(steps):
        nxt = int(np.argmax(forward(np.array(toks))[-1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def test_engine_greedy_matches_dense_reference(engine):
    """Both replicas, mixed prompt lengths and batch buckets: token
    sequences identical to the numpy reference; zero retraces."""
    eng = engine
    base = bfm.counter("bluefog_retrace_after_warmup_total").total()
    steps = 6
    prompt = [5, 11, 2, 7, 19, 3]
    want = _ref_greedy(eng, prompt, steps)
    idle_t, idle_s, idle_l = eng.idle_lane()

    nxt, logits = eng.prefill(0, 0, prompt)
    assert logits.shape == (eng.cfg.vocab,)
    got, lens, tok = [nxt], len(prompt), nxt
    for _ in range(steps - 1):
        gen = eng.decode(np.array([[tok], [idle_t]], np.int32),
                         np.array([[0], [idle_s]], np.int32),
                         np.array([[lens], [idle_l]], np.int32))
        tok = int(gen[0, -1, 0])
        got.append(tok)
        lens += 1
    assert got == want

    # second request on replica 1, shorter prompt (smaller prefill
    # bucket), decoded in the 2-lane batch bucket alongside replica 0
    p2 = [9, 1, 4]
    w2 = _ref_greedy(eng, p2, steps)
    t2, _ = eng.prefill(1, 2, p2)
    g2, l2 = [t2], len(p2)
    for _ in range(steps - 1):
        gen = eng.decode(np.array([[tok, idle_t], [t2, idle_t]], np.int32),
                         np.array([[0, idle_s], [2, idle_s]], np.int32),
                         np.array([[lens, idle_l], [l2, idle_l]], np.int32))
        t2 = int(gen[1, -1, 0])
        g2.append(t2)
        l2 += 1
        lens += 1
    assert g2 == w2
    assert bfm.counter(
        "bluefog_retrace_after_warmup_total").total() == base


def test_kv_slot_reuse_after_evict(engine):
    """A slot that served one request is reused for another: the second
    request's tokens are identical to running it in a never-used slot —
    stale KV rows beyond `lens` are masked, never read."""
    eng = engine
    idle_t, idle_s, idle_l = eng.idle_lane()

    def rollout(prompt, slot, steps=5):
        nxt, _ = eng.prefill(0, slot, prompt)
        out, lens, tok = [nxt], len(prompt), nxt
        for _ in range(steps - 1):
            gen = eng.decode(np.array([[tok], [idle_t]], np.int32),
                             np.array([[slot], [idle_s]], np.int32),
                             np.array([[lens], [idle_l]], np.int32))
            tok = int(gen[0, -1, 0])
            out.append(tok)
            lens += 1
        return out

    rollout([7, 7, 7, 7, 7, 7, 7], 1)           # dirty slot 1 (long ctx)
    dirty = rollout([3, 1, 4], 1)               # reuse slot 1 (shorter)
    fresh = rollout([3, 1, 4], 3)               # never-used slot
    assert dirty == fresh
    assert bfm.counter("bluefog_retrace_after_warmup_total").total() == 0


def test_bucketed_shapes_never_retrace(engine):
    """Every declared bucket visited twice (prefill lengths straddling
    both pad buckets, decode at 1 and 2 lanes): the jit caches stay at
    their post-warmup size."""
    eng = engine
    snap = (eng._prefill_jit._cache_size(), eng._decode_jit._cache_size())
    idle_t, idle_s, idle_l = eng.idle_lane()
    for rep in range(2):
        for prompt in ([1, 2], [1, 2, 3, 4], [1] * 5, [1] * 8):
            eng.prefill(rep, 0, prompt)
        for S in eng.scfg.batch_buckets:
            toks = np.full((2, S), idle_t, np.int32)
            slots = np.full((2, S), idle_s, np.int32)
            lens = np.full((2, S), idle_l, np.int32)
            toks[rep, 0], slots[rep, 0], lens[rep, 0] = 1, 0, 3
            eng.decode(toks, slots, lens)
    assert (eng._prefill_jit._cache_size(),
            eng._decode_jit._cache_size()) == snap
    assert bfm.counter("bluefog_retrace_after_warmup_total").total() == 0
    with pytest.raises(ValueError, match="exceeds the largest"):
        eng.prefill(0, 0, list(range(9)))       # undeclared shape refused


# ---------------------------------------------------------------------------
# Float64 decode oracle: the models/transformer cached-decode path
# ---------------------------------------------------------------------------

_ORACLE_SCRIPT = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "1"
import json
import jax
import jax.numpy as jnp
import numpy as np
from bluefog_tpu.models.transformer import (RingTransformerLM,
                                            init_decode_cache)


def max_diff(num_kv_heads):
    model = RingTransformerLM(vocab_size=61, num_layers=2, num_heads=4,
                              num_kv_heads=num_kv_heads, d_model=32,
                              max_seq_len=64, rope=True,
                              dtype=jnp.float64)
    B, T = 2, 12
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 61, (B, T)), jnp.int32)
    vars_ = model.init(jax.random.PRNGKey(0), toks[:, :1])
    full = model.apply(vars_, toks)                     # [B, T, V]

    # prefill the first 4 tokens as one cached chunk, then decode the
    # rest token by token; every step must match the full forward's
    # logits at that position exactly
    cache = init_decode_cache(model, B, 64)
    logits, cache = model.apply(vars_, toks[:, :4], pos_offset=0,
                                cache=cache)
    worst = float(jnp.abs(logits - full[:, :4]).max())
    for t in range(4, T):
        logits, cache = model.apply(vars_, toks[:, t:t + 1], pos_offset=t,
                                    cache=cache)
        worst = max(worst, float(jnp.abs(logits[:, 0] - full[:, t]).max()))
    return worst


print(json.dumps({"mha": max_diff(None), "gqa": max_diff(2)}))
"""


def test_float64_decode_oracle():
    """The cached decode path is logit-identical (float64, ~1e-12) to the
    full forward, for both MHA and grouped-query attention — the numeric
    foundation the serve engine's correctness claim stands on."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BLUEFOG_")
           and k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_ENABLE_X64")}
    p = subprocess.run([sys.executable, "-c", _ORACLE_SCRIPT],
                       cwd=REPO, capture_output=True, text=True,
                       timeout=420, env=env)
    assert p.returncode == 0, p.stderr[-3000:]
    doc = json.loads(p.stdout.strip().splitlines()[-1])
    assert doc["mha"] < 1e-12, doc
    assert doc["gqa"] < 1e-12, doc


# ---------------------------------------------------------------------------
# The 8-rank train→serve estate
# ---------------------------------------------------------------------------

def _estate(cpu_devices, seed=99):
    """2 training replicas (pp=2) on devices 0-3, 2 serving replicas
    (pp=2) on devices 4-7; deliberately different initial weights so a
    pull is observable."""
    import optax
    import bluefog_tpu.optimizers as bfopt

    cfg = compose.LMConfig(**_CFG)
    train_m = compose.compose_parallelism(2, 2, 1, 1,
                                          devices=cpu_devices[:4])
    serve_m = compose.compose_parallelism(2, 2, 1, 1,
                                          devices=cpu_devices[4:])
    grad_fn = compose.make_lm_grad_fn(cfg, train_m)
    step, strategy = compose.make_train_step(
        train_m, grad_fn, optax.sgd(0.05))
    train_params = compose.init_lm_params(cfg, train_m, seed=1)
    state = bfopt.init_distributed(strategy, train_params)
    toks = compose.make_lm_batch(cfg, train_m)
    train_params = compose.device_put(train_m, train_params)

    scfg = ServeConfig(batch_buckets=(1, 2, 4), prefill_buckets=(4, 8),
                       slots=4, max_len=32)
    eng = ServeEngine(serve_m, cfg,
                      compose.init_lm_params(cfg, serve_m, seed=seed), scfg)
    eng.warmup()
    return cfg, train_m, (step, state, train_params, toks), eng


def test_e2e_serving_while_training_advances(cpu_devices):
    """16 concurrent requests drain while the training fleet advances and
    the refresher pulls mid-traffic: staleness rises with train steps and
    drops to 0 on pull, pulled weights equal the training average, the KV
    donation stays intact, and nothing retraces."""
    cfg, train_m, (step, state, train_params, toks), eng = \
        _estate(cpu_devices)
    refresher = WeightRefresher(eng, train_m, every=2)
    sched = Scheduler(eng)
    cache_probe = eng.cache["k"]

    rng = np.random.default_rng(0)
    reqs = [sched.submit(rng.integers(0, cfg.vocab,
                                      int(rng.integers(2, 9))).tolist(),
                         max_new_tokens=int(rng.integers(2, 6)))
            for _ in range(16)]
    assert sched.pending + sched.in_flight == 16

    train_done, stal_seen, pulls = 0, [], 0
    guard = 0
    while not sched.done:
        guard += 1
        assert guard < 500, "scheduler failed to drain"
        sched.step()
        if train_done < 4:
            train_params, state, _ = step(train_params, state, toks)
            train_done += 1
            refresher.note_train_step(train_done)
            stal_seen.append(refresher.staleness())
            if refresher.maybe_refresh(train_params, train_done):
                pulls += 1
                assert refresher.staleness() == 0.0   # gauge drops on pull

    assert len(sched.completed) == 16
    assert all(len(r.generated) == r.max_new_tokens for r in reqs)
    assert pulls >= 1 and max(stal_seen) >= 1.0
    assert int(bfm.counter("bluefog_tokens_generated_total").total()) == \
        sum(r.max_new_tokens for r in reqs)

    # a pull delivers the training average at matching slice offsets
    refresher.pull(train_params, train_done)
    tp = np.asarray(train_params["blocks"]["wqkv"])
    sp = np.asarray(eng.params["blocks"]["wqkv"])
    for j in range(4):
        o = j % train_m.slice_size
        want = (tp[o] + tp[train_m.slice_size + o]) / 2
        np.testing.assert_allclose(sp[j], want, rtol=1e-5, atol=1e-7)

    assert cache_probe.is_deleted()               # donated into decode
    assert bfm.counter("bluefog_retrace_after_warmup_total").total() == 0
    sched.close()


def test_chaos_drill_kill_serving_replica(cpu_devices, tmp_path):
    """A serving replica dies mid-stream (chaos kill on its lead rank):
    its in-flight requests requeue at the head of the queue and EVERY
    request completes on the survivors — zero failures — the refresher
    keeps pulling through the healed topology, and the flight bundle +
    postmortem blame the right rank, with the serve block carrying the
    requeued count."""
    cfg, train_m, (step, state, train_params, toks), eng = \
        _estate(cpu_devices)
    refresher = WeightRefresher(eng, train_m, every=2)
    sched = Scheduler(eng)
    n_train = train_m.size
    dead_replica = 1
    dead_rank = n_train + dead_replica * eng.m.slice_size   # its lead rank

    for i in range(8):
        sched.submit([1 + i, 2, 3, 4], max_new_tokens=4)
    sched.step()                                  # everything in flight
    victims = [r for r in sched._active[dead_replica].values()]
    assert victims, "replica 1 should hold lanes before the kill"

    bfchaos.install(f"kill:step=2,rank={dead_rank}")
    train_done = 0
    try:
        for s in range(1, 4):
            train_params, state, _ = step(train_params, state, toks)
            train_done = s
        raise AssertionError("chaos kill never fired")
    except bfchaos.RankKilled as e:
        assert e.rank == dead_rank
        replica = (e.rank - n_train) // eng.m.slice_size
        lost = sched.fail_replica(replica)
        refresher.mark_dead_serve_replica(replica)
    bfchaos.uninstall()

    assert sorted(r.id for r in lost) == sorted(r.id for r in victims)
    # evicted requests went to the HEAD of the queue, stamped as requeued
    assert all(r.state == "queued" and r.requeued == 1 for r in lost)
    assert [r.id for r in list(sched._queue)[:len(lost)]] == \
        [r.id for r in lost]
    assert sched.requeued_total == len(lost)
    assert bfm.counter("bluefog_requests_total").value(
        status="requeued") == len(lost)
    sched.drain()
    # zero failed requests across the event: the victims re-ran on the
    # survivor and every request completed in full
    assert len(sched.completed) == 8 and not sched.failed
    assert all(r.replica == 0 for r in sched.completed)
    assert all(len(r.generated) == r.max_new_tokens
               for r in sched.completed)
    assert all(r.requeued == 1 for r in lost)

    refresher.pull(train_params, train_done)      # healed topology pulls
    assert refresher.staleness() == 0.0

    bundle_path = tmp_path / "flight_rank0.json"
    bfflight.dump(str(bundle_path), reason="chaos drill")
    bundle = json.loads(bundle_path.read_text())
    sv = bundle["serve"]
    assert sv["dead_replicas"] == [dead_replica]
    assert sv["failed"] == [] and sv["requeued"] == len(lost)
    assert sv["last_request_ids"]["0"], sv

    pm = _load_tool("tools/postmortem")
    report = pm.analyze({0: bundle})
    assert report["verdict"]["first_failed_rank"] == dead_rank
    assert report["serve"]["dead_replicas"] == [dead_replica]
    assert report["serve"]["failed_request_ids"] == []
    sched.close()


# ---------------------------------------------------------------------------
# Serving checkpoints
# ---------------------------------------------------------------------------

def test_serving_checkpoint_roundtrip(tmp_path):
    d = str(tmp_path / "ckpt")
    params = {"blocks": {"w": np.arange(12, dtype=np.float32).reshape(3, 4)},
              "shared": {"e": np.ones((2, 2), np.float32)}}
    p = checkpoint.save_for_serving(d, params, step=7)
    assert os.path.basename(p) == "serving_step_7"
    checkpoint.save_for_serving(d, params, step=9)
    assert checkpoint.all_serving_steps(d) == [7, 9]
    assert checkpoint.latest_serving_step(d) == 9
    got, step = checkpoint.load_for_serving(d)
    assert step == 9
    np.testing.assert_array_equal(got["blocks"]["w"], params["blocks"]["w"])

    # torn export (no completion marker): skipped, older snapshot wins
    torn = os.path.join(d, "serving_step_11")
    os.makedirs(torn)
    assert checkpoint.latest_serving_step(d) == 9
    assert checkpoint.all_serving_steps(d, include_incomplete=True) == \
        [7, 9, 11]
    _, step = checkpoint.load_for_serving(d)
    assert step == 9


def test_serving_checkpoint_rejects_training_state(tmp_path):
    d = str(tmp_path / "ckpt")
    params = {"w": np.ones(3, np.float32)}
    with pytest.raises(ValueError, match="training tuple"):
        checkpoint.save_for_serving(d, (params, {"opt": 1}), step=0)
    with pytest.raises(ValueError, match="training state"):
        checkpoint.save_for_serving(d, {"params": params, "opt_state": 1},
                                    step=0)
    assert checkpoint.load_for_serving(d) == (None, None)


# ---------------------------------------------------------------------------
# Launcher surface
# ---------------------------------------------------------------------------

def test_launcher_serve_env(monkeypatch):
    from bluefog_tpu.run import launcher
    args = launcher.build_parser().parse_args(
        ["--serve", "--serve-buckets", "1,2,4@8,64",
         "--refresh-every", "5", "python", "serve.py"])
    env = launcher._child_env(args)
    assert env["BLUEFOG_SERVE"] == "1"
    assert env["BLUEFOG_SERVE_BUCKETS"] == "1,2,4@8,64"
    assert env["BLUEFOG_REFRESH_EVERY"] == "5"
    # without --serve none of the serving env leaks into the child
    args = launcher.build_parser().parse_args(["python", "x.py"])
    env = launcher._child_env(args)
    assert "BLUEFOG_SERVE" not in env


def test_launcher_serve_defaults_to_demo(monkeypatch):
    from bluefog_tpu.run import launcher
    calls = {}

    def fake_call(cmd, env=None):
        calls["cmd"], calls["env"] = cmd, env
        return 0

    monkeypatch.setattr(launcher.subprocess, "call", fake_call)
    assert launcher.main(["--serve"]) == 0
    assert calls["cmd"] == [sys.executable, "-m", "bluefog_tpu.serve"]
    assert calls["env"]["BLUEFOG_SERVE"] == "1"
    # an explicit command wins over the demo default
    assert launcher.main(["--serve", "python", "my_server.py"]) == 0
    assert calls["cmd"] == ["python", "my_server.py"]
