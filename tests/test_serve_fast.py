"""bluefog_tpu.serve fast path: speculative decoding, prefix pages, int8 KV.

What is pinned here:

* **speculative bit-identity** — ``ServeEngine.spec_decode`` through the
  scheduler produces EXACTLY the plain-greedy token streams (the accept
  rule emits target-argmax tokens only; speculation changes how many
  arrive per call, never which);
* **zero retraces under speculation** — draft + verify-chunk programs are
  compiled at warmup for every batch bucket; a sweep over all buckets
  leaves the retrace sentinel at 0;
* **prefix copy-on-write** — two requests sharing a sealed prefix page
  and then diverging produce byte-identical streams to an engine with
  sharing disabled (sharers can never contaminate each other, and a hit
  is actually recorded);
* **the float64 quantization oracle** — int8/fp8 page storage bounds the
  attention-output drift vs raw float64 pages (int8/fp8 < 5e-2 on unit
  normal kv; raw is exact to 1e-12) — the documented drift bound the KV
  bytes/token halving is priced against;
* **fused sampling determinism** — re-seeding a slot replays the exact
  sampled stream (per-slot PRNG keys live in the decode scan carry);
* **allocator scaling** — the heap free-list stays fast at 50k slots
  (the microbench assert behind the O(log n) claim);
* **config surface** — ``_parse_buckets`` / ``from_env`` reject malformed
  ``BLUEFOG_SPEC_DECODE`` / ``BLUEFOG_KV_DTYPE`` / ``BLUEFOG_PREFIX_PAGES``
  specs naming the offending token and the expected grammar; the
  greedy-only speculation rule; ``DraftCarve`` / ``apply_rope_grid``
  units.
"""
import dataclasses
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bluefog_tpu.models.transformer import apply_rope_grid, apply_rope_rows
from bluefog_tpu.parallel import compose
from bluefog_tpu.parallel.compose import draft_carve
from bluefog_tpu.serve import Scheduler, ServeConfig, ServeEngine
from bluefog_tpu.serve.engine import _parse_buckets
from bluefog_tpu.serve.kv_cache import (KVCacheConfig, PrefixCache,
                                        SlotAllocator, attend_rows,
                                        dequantize_rows, quantize_rows,
                                        store_dtype)
from bluefog_tpu.utils import flight as bfflight
from bluefog_tpu.utils import metrics as bfm

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

_CFG = dict(vocab=32, d_model=32, heads=4, layers=4, seq_len=32)


@pytest.fixture(autouse=True)
def _clean():
    bfm.reset_metrics()
    bfflight.reset()
    yield
    bfflight.reset()
    bfm.reset_metrics()


# ---------------------------------------------------------------------------
# Quantized page storage units
# ---------------------------------------------------------------------------

def test_quantize_roundtrip_int8():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(5, 7, 2, 8)), jnp.float32)
    q, scale = quantize_rows(x, "int8")
    assert q.dtype == jnp.int8 and scale.shape == x.shape[:-1]
    back = dequantize_rows(q, scale, jnp.float32)
    err = float(jnp.abs(back - x).max())
    amax = float(jnp.abs(x).max())
    assert err <= amax / 127.0 + 1e-6          # half-ulp of the amax grid
    assert err > 0                             # it actually quantized


def test_quantize_roundtrip_fp8():
    if not hasattr(jnp, "float8_e4m3fn"):
        pytest.skip("no fp8 dtype in this jax build")
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 3, 8)), jnp.float32)
    q, scale = quantize_rows(x, "fp8")
    assert q.dtype == jnp.float8_e4m3fn
    back = dequantize_rows(q, scale, jnp.float32)
    # e4m3 keeps ~2 significant digits; amax-scaled error stays relative
    assert float(jnp.abs(back - x).max()) < 0.1 * float(jnp.abs(x).max())


def test_quantize_raw_identity():
    x = jnp.ones((2, 3, 4))
    q, scale = quantize_rows(x, "raw")
    assert scale is None and q is x
    assert dequantize_rows(q, None, jnp.float32).dtype == jnp.float32
    with pytest.raises(ValueError, match="unknown KV store"):
        quantize_rows(x, "int4")
    with pytest.raises(ValueError, match="unknown KV store"):
        store_dtype("nvfp4")


def test_kv_config_quantized_bytes():
    kw = dict(layers=2, slots=4, max_len=16, kv_heads=2, head_dim=8)
    raw = KVCacheConfig(**kw)
    q8 = KVCacheConfig(store="int8", **kw)
    assert not raw.quantized and q8.quantized
    # f32 payload: 4 B/elem; int8 payload: 1 B/elem + one f32 scale per
    # (position, head) — at head_dim 8 that is (8 + 4) / 32 of raw
    assert raw.bytes_per_token() == 2 * 2 * 2 * 8 * 4
    assert q8.bytes_per_token() == 2 * 2 * 2 * (8 + 4)
    assert q8.bytes_per_token() <= raw.bytes_per_token() // 2
    assert q8.bytes() < raw.bytes()
    # prefix pages add physical rows behind the request slots
    pc = KVCacheConfig(prefix_slots=2, **kw)
    assert pc.rows == 4 + 2 + 1 and pc.trash_slot == 6
    assert pc.prefix_row(0) == 4 and pc.prefix_row(1) == 5
    with pytest.raises(ValueError, match="out of range"):
        pc.prefix_row(2)


def test_quantized_kv_float64_drift_oracle():
    """attend_rows over int8/fp8 pages vs raw float64 pages: the drift
    bound docs/SERVING.md quotes for the bytes/token halving."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("BLUEFOG_")
           and k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_ENABLE_X64")}
    p = subprocess.run([sys.executable, "-c", _DRIFT_SCRIPT],
                       cwd=REPO, capture_output=True, text=True,
                       timeout=420, env=env)
    assert p.returncode == 0, p.stderr[-3000:]
    doc = json.loads(p.stdout.strip().splitlines()[-1])
    assert doc["raw"] < 1e-12, doc             # raw pages are exact
    assert 0 < doc["int8"] < 5e-2, doc         # the SERVING.md drift bound
    if doc["fp8"] is not None:
        assert 0 < doc["fp8"] < 1e-1, doc      # e4m3: ~2 significant digits


_DRIFT_SCRIPT = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "1"
import json
import jax.numpy as jnp
import numpy as np
from bluefog_tpu.serve.kv_cache import attend_rows, quantize_rows

rng = np.random.default_rng(0)
S, L, H, D = 3, 24, 4, 16
slots = jnp.arange(S, dtype=jnp.int32)
lengths = jnp.asarray([7, 15, 23], jnp.int32)
q = jnp.asarray(rng.normal(size=(S, H, D)))
k = jnp.asarray(rng.normal(size=(S, H, L, D)))
v = jnp.asarray(rng.normal(size=(S, H, L, D)))
ref = attend_rows(q, k, v, slots, lengths)          # float64 raw oracle


def drift(store):
    qk, sk = quantize_rows(k, store)
    qv, sv = quantize_rows(v, store)
    out = attend_rows(q, qk, qv, slots, lengths, k_scale=sk, v_scale=sv)
    return float(jnp.abs(out - ref).max())


fp8 = drift("fp8") if hasattr(jnp, "float8_e4m3fn") else None
raw = float(jnp.abs(
    attend_rows(q, k.astype(jnp.float64), v.astype(jnp.float64),
                slots, lengths) - ref).max())
print(json.dumps({"raw": raw, "int8": drift("int8"), "fp8": fp8}))
"""


# ---------------------------------------------------------------------------
# PrefixCache + SlotAllocator units
# ---------------------------------------------------------------------------

def test_prefix_cache_admit_seal_acquire_release():
    pc = PrefixCache(pages=2, page_tokens=4, first_row=8, replica=1)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]        # share_len = 8 (two pages)
    assert pc.match(prompt) is None
    assert pc.acquire(prompt) is None            # miss counted
    row, plen = pc.admit(prompt)
    assert row == 8 and plen == 8
    assert pc.acquire(prompt) is None            # admitted but not sealed
    pc.seal(row)
    got = pc.acquire(prompt)
    assert got == (8, 8)
    hits = bfm.get_metric("bluefog_serve_prefix_hits_total")
    misses = bfm.get_metric("bluefog_serve_prefix_misses_total")
    assert hits.total() == 1 and misses.total() == 2
    # attach refcounts without touching hit/miss metrics
    pc.attach(row)
    assert hits.total() == 1
    pc.release(row)
    pc.release(row)
    with pytest.raises(ValueError, match="not acquired"):
        pc.release(row)
    # whole pages only, with >= 1 token left over for the request
    assert pc._share_len([1, 2, 3, 4]) == 0      # no leftover token
    assert pc._share_len([1, 2, 3, 4, 5]) == 4
    assert pc.admit([1, 2, 3]) is None
    d = pc.describe()
    assert d["resident"][0]["sealed"] and d["resident"][0]["digest"]


def test_prefix_cache_lru_eviction():
    pc = PrefixCache(pages=2, page_tokens=2, first_row=4)
    r0, _ = pc.admit([1, 1, 9])
    pc.seal(r0)
    r1, _ = pc.admit([2, 2, 9])
    pc.seal(r1)
    assert pc.in_use == 2
    pc.acquire([1, 1, 9])                        # refs r0; r1 is idle LRU
    r2, _ = pc.admit([3, 3, 9])
    assert r2 == r1                              # evicted the idle entry
    assert pc.match([2, 2, 9]) is None
    assert pc.match([1, 1, 9]) is not None
    pc.seal(r2)
    pc.acquire([3, 3, 9])
    assert pc.admit([4, 4, 9]) is None           # everything pinned
    # re-admitting a resident prefix reuses its row instead of a new one
    assert pc.admit([1, 1, 9]) == (r0, 2)


def test_slot_allocator_heap_microbench():
    """50k alloc + 50k free through the heap free-list in well under a
    second — the O(log n) bound behind paged-sharing slot counts (the
    sorted-list predecessor was O(n log n) per free)."""
    n = 50_000
    a = SlotAllocator(n)
    t0 = time.perf_counter()
    slots = [a.alloc() for _ in range(n)]
    for s in slots:
        a.free(s)
    dt = time.perf_counter() - t0
    assert a.in_use == 0
    assert dt < 2.0, f"alloc/free of {n} slots took {dt:.2f}s"
    # lowest-free-first survives the heap rewrite (slot-reuse tests pin it)
    b = SlotAllocator(4)
    assert [b.alloc() for _ in range(4)] == [0, 1, 2, 3]
    b.free(2)
    b.free(0)
    assert b.alloc() == 0 and b.alloc() == 2


# ---------------------------------------------------------------------------
# Config surface: bucket grammar, env parsing, fast-path validation
# ---------------------------------------------------------------------------

def test_parse_buckets_names_offending_token():
    with pytest.raises(ValueError, match=r"bad batch bucket token 'x'"):
        _parse_buckets("1,x@8")
    with pytest.raises(ValueError, match=r"bad prefill bucket token 'q'"):
        _parse_buckets("1,2@8,q")
    with pytest.raises(ValueError, match="expected"):
        _parse_buckets("1@2@3")
    with pytest.raises(ValueError, match="must be >= 1"):
        _parse_buckets("0,2@8")


@pytest.mark.parametrize("var,val,tok", [
    ("BLUEFOG_SPEC_DECODE", "x", "'x'"),
    ("BLUEFOG_SPEC_DECODE", "3@y", "'y'"),
    ("BLUEFOG_KV_DTYPE", "int4", "'int4'"),
    ("BLUEFOG_PREFIX_PAGES", "q", "'q'"),
    ("BLUEFOG_PREFIX_PAGES", "2xz", "'z'"),
    ("BLUEFOG_DECODE_KERNEL", "mosaic", "'mosaic'"),
    ("BLUEFOG_DECODE_KERNEL", "pallas@w", "'w'"),
])
def test_from_env_rejects_bad_specs(monkeypatch, var, val, tok):
    monkeypatch.setenv(var, val)
    with pytest.raises(ValueError) as e:
        ServeConfig.from_env()
    msg = str(e.value)
    assert var in msg and tok in msg and "expected" in msg


def test_from_env_fast_paths(monkeypatch):
    monkeypatch.setenv("BLUEFOG_SPEC_DECODE", "3@1")
    monkeypatch.setenv("BLUEFOG_KV_DTYPE", "int8")
    monkeypatch.setenv("BLUEFOG_PREFIX_PAGES", "2x8")
    monkeypatch.setenv("BLUEFOG_DECODE_KERNEL", "pallas@8")
    cfg = ServeConfig.from_env()
    assert cfg.spec_decode == 3 and cfg.spec_stages == 1
    assert cfg.kv_dtype == "int8"
    assert cfg.prefix_pages == 2 and cfg.prefix_page_tokens == 8
    assert cfg.decode_kernel == "pallas" and cfg.decode_block_k == 8
    # explicit overrides beat the env
    assert ServeConfig.from_env(spec_decode=0).spec_decode == 0
    assert ServeConfig.from_env(decode_kernel="xla").decode_kernel == "xla"


def test_serve_config_fast_validation():
    with pytest.raises(ValueError, match="greedy-only"):
        ServeConfig(spec_decode=2, temperature=0.5)
    with pytest.raises(ValueError, match="kv_dtype"):
        ServeConfig(kv_dtype="int4")
    with pytest.raises(ValueError, match="prefix_page_tokens"):
        ServeConfig(prefix_pages=1, prefix_page_tokens=32,
                    prefill_buckets=(8, 16))
    with pytest.raises(ValueError, match="top_p"):
        ServeConfig(top_p=0.0)
    with pytest.raises(ValueError, match="temperature"):
        ServeConfig(temperature=-0.1)
    with pytest.raises(ValueError, match="decode_kernel"):
        ServeConfig(decode_kernel="cuda")
    with pytest.raises(ValueError, match="does not tile"):
        ServeConfig(decode_kernel="pallas", decode_block_k=24, max_len=64)
    with pytest.raises(ValueError, match="sublane"):
        ServeConfig(decode_kernel="pallas", decode_block_k=4, max_len=64)
    with pytest.raises(ValueError, match="mid-block"):
        ServeConfig(decode_kernel="pallas", decode_block_k=16,
                    prefix_pages=1, prefix_page_tokens=8)
    # block_k clamps to short caches: one block covering max_len is legal
    assert ServeConfig(decode_kernel="pallas", max_len=32,
                       prefill_buckets=(8, 16)).decode_block_k == 128
    assert ServeConfig(decode_steps_per_call=2).decode_window == 2
    assert ServeConfig(spec_decode=3).decode_window == 4


def test_draft_carve(cpu_devices):
    cfg = compose.LMConfig(**_CFG)
    m = compose.compose_parallelism(2, 2, 2, 1, devices=cpu_devices)
    dc = draft_carve(m, cfg, 1)
    assert dc.layers == 2 and dc.total_layers == 4
    assert dc.logit_stage == 1                  # one hop past stage 0
    assert 0.0 < dc.cost_fraction < 1.0
    full = draft_carve(m, cfg, 2)               # identity draft
    assert full.logit_stage == 0 and full.n_params == cfg.n_params
    assert full.cost_fraction == 1.0
    with pytest.raises(ValueError, match="draft stages"):
        draft_carve(m, cfg, 0)
    with pytest.raises(ValueError, match="draft stages"):
        draft_carve(m, cfg, 3)
    assert "stages" in dc.describe()


def test_apply_rope_grid_matches_rows():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(3, 5, 2, 8)), jnp.float32)
    pos = jnp.asarray(rng.integers(0, 30, (3, 5)), jnp.int32)
    grid = apply_rope_grid(x, pos)
    for t in range(5):                           # column t == rows at pos[:, t]
        rows = apply_rope_rows(x[:, t], pos[:, t])
        np.testing.assert_array_equal(np.asarray(grid[:, t]),
                                      np.asarray(rows))
    with pytest.raises(ValueError, match="even head_dim"):
        apply_rope_grid(x[..., :7], pos)


# ---------------------------------------------------------------------------
# The fast engine on the 8-rank virtual mesh (dp=2, pp=2, tp=2)
# ---------------------------------------------------------------------------

_SCFG = dict(batch_buckets=(1, 2), prefill_buckets=(4, 8), slots=4,
             max_len=32, decode_steps_per_call=1)


@pytest.fixture(scope="module")
def fast_setup(cpu_devices):
    cfg = compose.LMConfig(**_CFG)
    m = compose.compose_parallelism(2, 2, 2, 1, devices=cpu_devices)
    params = compose.init_lm_params(cfg, m, seed=3)
    fast = ServeEngine(m, cfg, params, ServeConfig(
        spec_decode=2, spec_stages=1, prefix_pages=2, prefix_page_tokens=4,
        **_SCFG))
    fast.warmup()
    plain = ServeEngine(m, cfg, params, ServeConfig(**_SCFG))
    plain.warmup()
    return cfg, m, fast, plain


def _drain(engine, prompts, max_new=6):
    sched = Scheduler(engine)
    reqs = [sched.submit(p, max_new_tokens=max_new) for p in prompts]
    guard = 0
    while not sched.done:
        guard += 1
        assert guard < 10_000, "scheduler failed to drain"
        sched.step()
    sched.close()
    return reqs


def test_spec_decode_bit_identical_to_greedy(fast_setup):
    """The tentpole pin: speculative streams ARE the greedy streams."""
    _, _, fast, plain = fast_setup
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, _CFG["vocab"],
                            int(n)).tolist() for n in (3, 5, 8, 4, 6)]
    want = [r.generated for r in _drain(plain, prompts)]
    bfm.reset_metrics()
    got = [r.generated for r in _drain(fast, prompts)]
    assert got == want
    drafted = bfm.get_metric("bluefog_serve_spec_drafted_total")
    accepted = bfm.get_metric("bluefog_serve_spec_accepted_total")
    assert drafted is not None and drafted.total() > 0
    assert accepted is not None and 0 <= accepted.total() <= drafted.total()


def test_spec_bucket_sweep_zero_retraces(fast_setup):
    """Every draft + verify shape was compiled at warmup: sweeping all
    batch buckets (live + trash lanes) never retraces."""
    _, _, fast, _ = fast_setup
    sizes = fast._jit_sizes()
    nxt, _ = fast.prefill(0, 0, [5, 6, 7])
    for S in fast.scfg.batch_buckets:
        toks = np.zeros((fast.m.dp, S), np.int32)
        slots = np.full((fast.m.dp, S), fast.cache_cfg.trash_slot, np.int32)
        lens = np.zeros((fast.m.dp, S), np.int32)
        toks[0, 0], slots[0, 0], lens[0, 0] = nxt, 0, 3
        emitted, counts = fast.spec_decode(toks, slots, lens)
        assert emitted.shape == (fast.m.dp, S, fast.scfg.spec_decode + 1)
        assert 1 <= int(counts[0, 0]) <= fast.scfg.spec_decode + 1
        assert all(int(t) >= 0 for t in emitted[0, 0, :counts[0, 0]])
        assert all(int(t) == -1 for t in emitted[0, 0, counts[0, 0]:])
        nxt = int(emitted[0, 0, counts[0, 0] - 1])
        lens[0, 0] += int(counts[0, 0])
    assert fast._jit_sizes() == sizes
    assert bfm.counter("bluefog_retrace_after_warmup_total").total() == 0


def test_prefix_cow_no_cross_contamination(fast_setup):
    """Two sharers of one sealed prefix page diverge into private slots:
    both streams byte-match the engine with sharing disabled."""
    _, _, fast, plain = fast_setup
    shared = [3, 1, 4, 1]                        # one page (page_tokens=4)
    a = shared + [5, 9, 2]
    b = shared + [6, 5, 3, 5]
    want = [r.generated for r in _drain(plain, [a, b])]
    bfm.reset_metrics()
    reqs = _drain(fast, [a, b])
    assert [r.generated for r in reqs] == want
    hits = bfm.get_metric("bluefog_serve_prefix_hits_total")
    assert hits is not None and hits.total() >= 1
    assert any(r.prefix_len == 4 for r in reqs)


@pytest.fixture(scope="module")
def flash_setup(cpu_devices):
    """Two engines differing ONLY in decode_kernel: every fast path on
    (spec decode + shared prefix pages), xla vs pallas flash decode."""
    cfg = compose.LMConfig(**_CFG)
    m = compose.compose_parallelism(2, 2, 2, 1, devices=cpu_devices)
    params = compose.init_lm_params(cfg, m, seed=3)
    common = dict(batch_buckets=(1, 2), prefill_buckets=(4, 8, 16),
                  slots=4, max_len=32, decode_steps_per_call=1,
                  spec_decode=2, spec_stages=1,
                  prefix_pages=2, prefix_page_tokens=8)
    flash = ServeEngine(m, cfg, params, ServeConfig(
        decode_kernel="pallas", decode_block_k=8, **common))
    flash.warmup()
    ref = ServeEngine(m, cfg, params, ServeConfig(**common))
    ref.warmup()
    return flash, ref


def test_flash_decode_engine_bit_identical(flash_setup):
    """The serving acceptance gate for the Pallas flash-decode kernel:
    with identical configs, the kernel engine's token streams ARE the XLA
    engine's streams — through 1-token decode (flash_attend_rows), the
    k-token speculative verify (flash_attend_chunk), ragged mixed-length
    batches, and prefix-hit lanes routed through the shared page."""
    flash, ref = flash_setup
    rng = np.random.default_rng(13)
    shared = rng.integers(0, _CFG["vocab"], 8).tolist()   # one sealed page
    prompts = [rng.integers(0, _CFG["vocab"], int(n)).tolist()
               for n in (3, 5, 8, 14)]
    sharers = [shared + [5, 9, 2], shared + [6, 5, 3, 5]]
    want = [r.generated for r in _drain(ref, prompts)]
    want += [r.generated for r in _drain(ref, sharers)]
    got = [r.generated for r in _drain(flash, prompts)]
    bfm.reset_metrics()
    got += [r.generated for r in _drain(flash, sharers)]
    assert got == want
    # the prefix-hit kernel path really engaged (a sharer rode the page)
    hits = bfm.get_metric("bluefog_serve_prefix_hits_total")
    assert hits is not None and hits.total() >= 1
    assert bfm.counter("bluefog_retrace_after_warmup_total").total() == 0


def test_sampling_determinism(cpu_devices):
    """temperature > 0: per-slot PRNG keys ride the decode-scan carry —
    the same seed and the same admission sequence replay the exact
    sampled stream (each admission folds a counter into the key, so
    slot reuse by a LATER request never replays an earlier one)."""
    cfg = compose.LMConfig(**_CFG)
    m = compose.compose_parallelism(2, 2, 2, 1, devices=cpu_devices)
    params = compose.init_lm_params(cfg, m, seed=3)
    eng = ServeEngine(m, cfg, params, ServeConfig(
        temperature=0.9, top_p=0.8, seed=11, **_SCFG))
    eng.warmup()

    def run():
        eng._seed_count = 0                      # replay the admission order
        nxt, _ = eng.prefill(0, 0, [5, 6, 7])
        out, pos = [nxt], 3
        for _ in range(6):
            toks = np.zeros((m.dp, 1), np.int32)
            slots = np.full((m.dp, 1), eng.cache_cfg.trash_slot, np.int32)
            lens = np.zeros((m.dp, 1), np.int32)
            toks[0, 0], slots[0, 0], lens[0, 0] = out[-1], 0, pos
            gen = eng.decode(toks, slots, lens)
            out.append(int(gen[0, 0, 0]))
            pos += 1
        return out

    first, second = run(), run()
    assert first == second
    assert all(0 <= t < _CFG["vocab"] for t in first)
    # greedy config rejects a sampled-only code path ever engaging
    assert bfm.counter("bluefog_retrace_after_warmup_total").total() == 0
