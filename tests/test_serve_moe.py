"""Expert-parallel MoE serving: decode-regime dropless, spec, refresh.

What is pinned here (ISSUE 19):

* **config surface** — the ``BLUEFOG_SERVE_MOE`` / ``--serve-moe``
  grammar with named malformed-token errors, ServeConfig's eager
  ``moe_serving_ep_mismatch`` check, the engine's knob-vs-model
  cross-validation, and the named ``moe_serving_requires_topk_router``
  refusal for expert-choice models at serve time;
* **decode-regime dropless** — the ``decode_tile`` policy, a T x k
  battery at decode-shaped token counts (T in {1, 4, 8}, k in {1, 2})
  including the adversarial all-tokens-to-one-expert routing, the
  bit-exact identity of dispatch∘combine at tiny T, and small-tile
  Pallas-vs-XLA forward equality (sublane padding under tile < 8);
* **engine correctness** — MoE greedy decode on an ep=2 carving matches
  an independent numpy top-k-mixture reference token-for-token; a
  float64 subprocess oracle pins the dropless grouped path against the
  dense-equivalent (no-drop) mixture to 1e-12 through a real greedy
  decode loop;
* **fused-decode invariants** — KV-cache donation intact and retrace
  sentinel 0 across a mixed-bucket sweep on the MoE engine;
* **speculative decoding** — dense-FFN-draft spec decode emits streams
  bit-identical to plain MoE greedy (the accept rule only ever emits
  target-argmax tokens);
* **weight refresh** — the refresher pulls router + expert tables
  through the combined mesh (MoE leaves need no special casing) and
  refuses ep / num_experts layout mismatches by name;
* **expert-load-aware batching** — the scheduler publishes hot-expert /
  router-entropy gauges from ``engine.moe_load()`` and its admission
  tiebreak prefers the replica with less expert skew;
* **the launcher surface** — ``--serve-moe`` threads into the child's
  ``BLUEFOG_SERVE_MOE``.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.moe.dropless import decode_tile, grouped_ffn_xla
from bluefog_tpu.moe.model import MoELMConfig, init_moe_params
from bluefog_tpu.parallel import compose
from bluefog_tpu.parallel.expert import moe_apply_dropless
from bluefog_tpu.serve import (Scheduler, ServeConfig, ServeEngine,
                               WeightRefresher)
from bluefog_tpu.serve.engine import _parse_serve_moe
from bluefog_tpu.utils import flight as bfflight
from bluefog_tpu.utils import metrics as bfm

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

E = 4                               # experts in every battery config


@pytest.fixture(autouse=True)
def _clean():
    bfm.reset_metrics()
    bfflight.reset()
    yield
    bfflight.reset()
    bfm.reset_metrics()


# ---------------------------------------------------------------------------
# Config grammar + eager contracts
# ---------------------------------------------------------------------------

def test_parse_serve_moe_grammar():
    assert _parse_serve_moe("8") == (8, 1, 1, 0)
    assert _parse_serve_moe("8x2") == (8, 2, 1, 0)
    assert _parse_serve_moe("8x2@2") == (8, 2, 2, 0)
    assert _parse_serve_moe("8x2@2:4") == (8, 2, 2, 4)
    assert _parse_serve_moe("16@4") == (16, 1, 4, 0)
    for bad in ("", "x2", "8.5", "8xtwo", "8@zero", "8:none"):
        with pytest.raises(ValueError, match="BLUEFOG_SERVE_MOE"):
            _parse_serve_moe(bad)
    for bad in ("0", "8x0", "8@0", "8:0"):
        with pytest.raises(ValueError, match="must be >= 1"):
            _parse_serve_moe(bad)


def test_serve_config_moe_validation():
    scfg = ServeConfig(moe_experts=8, moe_top_k=2, moe_ep=2, moe_tile=4)
    assert (scfg.moe_experts, scfg.moe_ep) == (8, 2)
    with pytest.raises(ValueError, match="moe_experts must be >= 0"):
        ServeConfig(moe_experts=-1)
    with pytest.raises(ValueError, match="moe_top_k"):
        ServeConfig(moe_experts=8, moe_top_k=3)
    # the ep carve must divide the expert table, offender named
    with pytest.raises(ValueError,
                       match="moe_serving_ep_mismatch.*moe_ep=3"):
        ServeConfig(moe_experts=8, moe_ep=3)
    with pytest.raises(ValueError, match="moe_tile"):
        ServeConfig(moe_experts=8, moe_tile=9)


def test_serve_config_moe_from_env(monkeypatch):
    monkeypatch.setenv("BLUEFOG_SERVE_MOE", "8x2@2:4")
    scfg = ServeConfig.from_env()
    assert (scfg.moe_experts, scfg.moe_top_k, scfg.moe_ep,
            scfg.moe_tile) == (8, 2, 2, 4)
    monkeypatch.setenv("BLUEFOG_SERVE_MOE", "8x2@3")
    with pytest.raises(ValueError, match="moe_serving_ep_mismatch"):
        ServeConfig.from_env()


def _moe_cfg(**kw):
    base = dict(vocab=32, d_model=16, heads=4, layers=2, seq_len=32,
                micro=1, batch=2, num_experts=E, top_k=2,
                dispatch="dropless")
    base.update(kw)
    return MoELMConfig(**base)


def test_expert_choice_refused_at_serve(cpu_devices):
    cfg = _moe_cfg(router_mode="expert_choice")
    m = compose.compose_parallelism(1, 1, 1, 1, 2, num_experts=E,
                                    devices=cpu_devices[:2])
    params = init_moe_params(cfg, m, seed=0)
    with pytest.raises(ValueError,
                       match="moe_serving_requires_topk_router"):
        ServeEngine(m, cfg, params, ServeConfig(
            batch_buckets=(1,), prefill_buckets=(4,), slots=2, max_len=32))


def test_engine_knobs_cross_validated(cpu_devices):
    # a dense model with an MoE ServeConfig is refused by name ...
    dense = compose.LMConfig(vocab=32, d_model=16, heads=4, layers=2,
                             seq_len=32, micro=1, batch=2)
    dm = compose.compose_parallelism(1, 1, 1, 1, devices=cpu_devices[:1])
    dp = compose.init_lm_params(dense, dm, seed=0)
    with pytest.raises(ValueError, match="drop the knob"):
        ServeEngine(dm, dense, dp, ServeConfig(
            batch_buckets=(1,), prefill_buckets=(4,), slots=2, max_len=32,
            moe_experts=E))
    # ... and declared knobs must agree with the model/carving
    cfg = _moe_cfg()
    m = compose.compose_parallelism(1, 1, 1, 1, 2, num_experts=E,
                                    devices=cpu_devices[:2])
    params = init_moe_params(cfg, m, seed=0)
    with pytest.raises(ValueError, match="moe_experts=8 does not match"):
        ServeEngine(m, cfg, params, ServeConfig(
            batch_buckets=(1,), prefill_buckets=(4,), slots=2, max_len=32,
            moe_experts=8, moe_ep=2))


# ---------------------------------------------------------------------------
# Decode-regime dropless: tile policy + T x k battery
# ---------------------------------------------------------------------------

def test_decode_tile_policy():
    # smallest pow2 covering ceil(max_rows / groups), capped at 8
    assert decode_tile(1, 2) == 1       # one lane, two local experts
    assert decode_tile(4, 2) == 2
    assert decode_tile(8, 2) == 4
    assert decode_tile(64, 2) == 8      # cap: stream wider, not taller
    assert decode_tile(3, 4) == 1
    assert decode_tile(9, 4) == 4       # ceil(9/4)=3 -> next pow2
    with pytest.raises(ValueError, match="moe_dropless_invalid_tile"):
        decode_tile(0, 2)
    with pytest.raises(ValueError, match="moe_dropless_invalid_tile"):
        decode_tile(8, 0)


def _run_dropless(devs, x, idx, grouped_fn, tile):
    """Drive moe_apply_dropless on a 2-device expert axis: ``x`` is
    ``[2, T, D]`` per-device rows, ``idx`` ``[2, T]`` global expert ids."""
    mesh = Mesh(np.array(devs[:2]), ("expert",))

    def f(xb, ib):
        return moe_apply_dropless(xb[0], ib[0], grouped_fn, None,
                                  axis="expert", num_experts=E,
                                  tile=tile)[None]

    fn = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P("expert"), P("expert")),
        out_specs=P("expert")))
    return np.asarray(fn(x, idx))


def test_decode_shaped_dropless_battery(cpu_devices):
    """T x k at decode shapes, with the tile the engine would pick:
    identity dispatch∘combine is bit-exact and expert-scaled routing
    follows the closed form — including the hostile all-to-one routing
    that would overflow any capacity path."""
    rng = np.random.default_rng(7)
    D = 8
    for T in (1, 4, 8):
        for k in (1, 2):
            rows = T * k                # choice-major rows one lane sends
            tile = decode_tile(2 * rows, E // 2)
            assert tile <= 8
            x = jnp.asarray(rng.normal(size=(2, rows, D)), jnp.float32)
            routings = [rng.integers(0, E, size=(2, rows)),
                        np.zeros((2, rows), np.int64)]       # hostile
            def scale(p, xt, eids):
                # eids are device-local; globalize before scaling
                geid = jax.lax.axis_index("expert") * (E // 2) + eids
                return xt * (geid + 1)[:, None, None].astype(xt.dtype)

            for idx_np in routings:
                idx = jnp.asarray(idx_np, jnp.int32)
                out = _run_dropless(cpu_devices, x, idx,
                                    lambda p, xt, eids: xt, tile)
                np.testing.assert_array_equal(out, np.asarray(x))
                scaled = _run_dropless(cpu_devices, x, idx, scale, tile)
                np.testing.assert_allclose(
                    scaled, np.asarray(x) * (idx_np + 1)[..., None],
                    rtol=1e-6)


def test_small_tile_pallas_matches_xla():
    """Tiles below the f32 sublane minimum (8) run through the kernel's
    pad-to-sublane path and must agree with the XLA batched einsum."""
    from bluefog_tpu.ops.pallas_moe import grouped_ffn_pallas
    rng = np.random.default_rng(3)
    D, F = 16, 32
    w1 = jnp.asarray(rng.normal(size=(E, D, F)), jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(E, F, D)), jnp.float32)
    for tile in (1, 2, 4, 8):
        G = 6
        xt = jnp.asarray(rng.normal(size=(G, tile, D)), jnp.float32)
        eid = jnp.asarray(rng.integers(0, E, size=(G,)), jnp.int32)
        ref = grouped_ffn_xla(xt, eid, w1, w2)
        got = grouped_ffn_pallas(xt, eid, w1, w2, interpret=True)
        assert got.shape == ref.shape == (G, tile, D)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# The MoE engine: greedy reference, invariants, spec, refresh, scheduler
# ---------------------------------------------------------------------------

_SCFG = dict(batch_buckets=(1, 2), prefill_buckets=(4, 8), slots=4,
             max_len=32, decode_steps_per_call=1,
             moe_experts=E, moe_top_k=2, moe_ep=2)


@pytest.fixture(scope="module")
def moe_engine(cpu_devices):
    """dp=2 x ep=2 greedy MoE engine on 4 virtual devices."""
    cfg = _moe_cfg()
    m = compose.compose_parallelism(2, 1, 1, 1, 2, num_experts=E,
                                    devices=cpu_devices[:4])
    params = init_moe_params(cfg, m, seed=5)
    eng = ServeEngine(m, cfg, params, ServeConfig(**_SCFG))
    eng.warmup()
    return eng


def _ref_moe_greedy(eng, prompt, steps):
    """Greedy decode via plain numpy: full forward per token, top-k
    mixture FFN over the full expert table reassembled from the ep
    peers' shards (replica 0; pp=tp=1)."""
    m, cfg = eng.m, eng.cfg
    Pt = jax.tree.map(np.asarray, eng.params)
    H, D = cfg.heads, cfg.d_model
    hsz = D // H
    k = cfg.top_k
    # replica 0's ep peers are device rows 0..ep-1 (slice-major layout)
    w1 = np.concatenate([Pt["experts"]["w1"][e] for e in range(m.ep)],
                        axis=1)          # [Lps, E, D, F]
    w2 = np.concatenate([Pt["experts"]["w2"][e] for e in range(m.ep)],
                        axis=1)
    wr = Pt["router"]["wr"][0]           # [Lps, D, E]

    def rope(x, pos):
        half = x.shape[-1] // 2
        freqs = 10000.0 ** (-np.arange(half) / half)
        ang = pos[:, None] * freqs[None]
        cos, sin = np.cos(ang)[:, None, :], np.sin(ang)[:, None, :]
        x1, x2 = x[..., :half], x[..., half:]
        return np.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                              -1)

    def ln(z):
        mu = z.mean(-1, keepdims=True)
        return (z - mu) / np.sqrt(z.var(-1, keepdims=True) + 1e-6)

    def gelu(g):
        return 0.5 * g * (1 + np.tanh(
            np.sqrt(2 / np.pi) * (g + 0.044715 * g ** 3)))

    def moe_ffn(h, li):
        logits = h @ wr[li]
        z = np.exp(logits - logits.max(-1, keepdims=True))
        probs = z / z.sum(-1, keepdims=True)
        idx = np.argsort(-probs, axis=-1, kind="stable")[:, :k]
        gate = np.take_along_axis(probs, idx, axis=-1)
        if k > 1:
            gate = gate / gate.sum(-1, keepdims=True)
        y = np.zeros_like(h)
        for j in range(k):
            for e in range(E):
                sel = idx[:, j] == e
                if sel.any():
                    y[sel] += gate[sel, j:j + 1] * (
                        gelu(h[sel] @ w1[li, e]) @ w2[li, e])
        return y

    def forward(toks):
        T = len(toks)
        pos = np.arange(T)
        x = Pt["shared"]["embed"][0][toks]
        for li in range(cfg.layers):
            h = ln(x)
            qkv = h @ Pt["blocks"]["wqkv"][0][li]
            q, kk, v = np.split(qkv, 3, -1)
            q = rope(q.reshape(T, H, hsz), pos)
            kk = rope(kk.reshape(T, H, hsz), pos)
            v = v.reshape(T, H, hsz)
            s = np.einsum("ihd,jhd->ihj", q * hsz ** -0.5, kk)
            mask = pos[:, None] >= pos[None, :]
            s = np.where(mask[:, None, :], s, -np.inf)
            p = np.exp(s - s.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            att = np.einsum("ihj,jhd->ihd", p, v).reshape(T, D)
            x = x + att @ Pt["blocks"]["wo"][0][li]
            x = x + moe_ffn(ln(x), li)
        return ln(x) @ Pt["shared"]["head"][0]

    toks, out = list(prompt), []
    for _ in range(steps):
        nxt = int(np.argmax(forward(np.array(toks))[-1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def test_moe_engine_matches_mixture_reference(moe_engine):
    """The dropless grouped decode path, end to end through the engine
    (ep=2 dispatch/combine, KV cache, bucketed shapes), emits the same
    greedy stream as the numpy top-k mixture reference."""
    eng = moe_engine
    sched = Scheduler(eng)
    prompts = [[3, 1, 2], [7, 6, 5, 4, 3, 2]]
    reqs = [sched.submit(p, max_new_tokens=5) for p in prompts]
    sched.drain()
    for r, p in zip(reqs, prompts):
        assert r.generated == _ref_moe_greedy(eng, p, 5), p
    sched.close()


def test_moe_mixed_buckets_zero_retrace_and_donation(moe_engine):
    """Every served shape hits a warm bucket across a mixed-length sweep
    — retrace sentinel stays 0 — and the KV cache is donated into each
    fused call (the pre-call buffer dies)."""
    eng = moe_engine
    base = bfm.counter("bluefog_retrace_after_warmup_total").total()
    probe = jax.tree.leaves(eng.cache)[0]
    sched = Scheduler(eng)
    rng = np.random.default_rng(0)
    for n in (2, 4, 3, 7, 8, 5):
        sched.submit(rng.integers(0, eng.cfg.vocab, n).tolist(),
                     max_new_tokens=4)
    sched.drain()
    sched.close()
    assert bfm.counter("bluefog_retrace_after_warmup_total").total() == base
    assert probe.is_deleted()
    load = eng.moe_load()
    assert load is not None and len(load) == eng.m.dp
    assert all(abs(sum(r["fractions"]) - 1.0) < 1e-6
               for r in load if r["tokens"])


def test_moe_spec_decode_bit_identical(cpu_devices, moe_engine):
    """Dense-FFN-draft speculative decoding emits token streams
    bit-identical to the plain-greedy MoE engine on the same prompts."""
    cfg = moe_engine.cfg
    m = compose.compose_parallelism(2, 1, 1, 1, 2, num_experts=E,
                                    devices=cpu_devices[:4])
    eng = ServeEngine(m, cfg, init_moe_params(cfg, m, seed=5),
                      ServeConfig(spec_decode=2, spec_stages=1, **_SCFG))
    eng.warmup()
    prompts = [[1, 2, 3], [9, 8, 7, 6, 5], [4, 4]]

    def drain(e):
        s = Scheduler(e)
        reqs = [s.submit(p, max_new_tokens=6) for p in prompts]
        s.drain()
        s.close()
        return [r.generated for r in reqs]

    assert drain(eng) == drain(moe_engine)
    drafted = bfm.counter("bluefog_serve_spec_drafted_total", "").total()
    assert drafted > 0


def test_refresher_pulls_expert_tables(cpu_devices, moe_engine):
    """The pull-only refresher moves router + expert-table leaves from a
    same-layout training carving — serve tables become bit-identical to
    the (single-replica) training tables."""
    eng = moe_engine
    train_m = compose.compose_parallelism(1, 1, 1, 1, 2, num_experts=E,
                                          devices=cpu_devices[4:6])
    train_params = init_moe_params(eng.cfg, train_m, seed=11)
    ref = WeightRefresher(eng, train_m, every=1)
    ref.pull(train_params, train_step=1)
    got = jax.tree.map(np.asarray, eng.params)
    want = jax.tree.map(np.asarray, train_params)
    for leaf in ("w1", "w2"):
        # serve rows repeat the training slice per replica (dp_train=1)
        np.testing.assert_array_equal(
            got["experts"][leaf],
            np.tile(want["experts"][leaf], (eng.m.dp, 1, 1, 1, 1)))
    np.testing.assert_array_equal(
        got["router"]["wr"],
        np.tile(want["router"]["wr"], (eng.m.dp, 1, 1, 1)))
    # restore the fixture engine's original weights for later tests
    eng.update_params(init_moe_params(eng.cfg, eng.m, seed=5))


def test_refresher_rejects_ep_layout_mismatch(cpu_devices, moe_engine):
    cfg_ep1 = _moe_cfg()
    train_m = compose.compose_parallelism(1, 1, 1, 1, 1, num_experts=E,
                                          devices=cpu_devices[4:5])
    cfg_ep1.validate(train_m)
    with pytest.raises(ValueError, match="ep=1"):
        WeightRefresher(moe_engine, train_m, every=1)


def test_scheduler_expert_load_gauges_and_skew(moe_engine):
    """Fabricated routing stats: the scheduler snapshot publishes the
    hot-expert / entropy gauges and the admission tiebreak prefers the
    replica with the flatter expert histogram."""
    eng = moe_engine
    sched = Scheduler(eng)
    # replica 0 flat (no skew), replica 1 all-on-one-expert (max skew):
    # [E counts..., entropy_sum, live_count]
    eng._route_stats = np.asarray(
        [[2.0, 2.0, 2.0, 2.0, 8.0 * np.log(E), 8.0],
         [8.0, 0.0, 0.0, 0.0, 0.0, 8.0]])
    sched._note_moe_load()
    hot = bfm.gauge("bluefog_serve_hot_expert_fraction", "")
    assert hot.value(replica=0) == pytest.approx(1.0 / E)
    assert hot.value(replica=1) == pytest.approx(1.0)
    ent = bfm.gauge("bluefog_serve_router_entropy", "")
    assert ent.value(replica=0) == pytest.approx(np.log(E))
    assert ent.value(replica=1) == pytest.approx(0.0)
    assert sched._expert_skew(0) == 0
    assert sched._expert_skew(1) == int((1.0 - 1.0 / E) * 8)
    block = sched._flight_block()
    assert block["moe"]["1"]["skew_eighths"] == sched._expert_skew(1)
    sched.close()


# ---------------------------------------------------------------------------
# float64 subprocess oracle: dropless grouped decode == dense mixture
# ---------------------------------------------------------------------------

_F64_ORACLE = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "1"
import json
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from bluefog_tpu.moe.layers import moe_ffn_dense, moe_ffn_dropless
from bluefog_tpu.moe.dropless import decode_tile

E, D, F, k = 4, 16, 32, 2
rng = np.random.default_rng(0)
wr = jnp.asarray(rng.normal(size=(D, E)))
w1 = jnp.asarray(rng.normal(size=(E, D, F)) * 0.1)
w2 = jnp.asarray(rng.normal(size=(E, F, D)) * 0.1)
head = jnp.asarray(rng.normal(size=(D, 29)) * 0.1)
embed = jnp.asarray(rng.normal(size=(29, D)))
mesh = Mesh(np.array(jax.devices("cpu")[:1]).reshape(1, 1),
            ("expert", "tp"))   # both FFN paths psum a (size-1) tp axis


def step_fn(kind):
    def f(h):
        if kind == "dense":
            y, _ = moe_ffn_dense(h, wr, w1, w2, top_k=k, axis="expert")
        else:
            y, _ = moe_ffn_dropless(h, wr, w1, w2, num_experts=E,
                                    top_k=k, axis="expert",
                                    tile=decode_tile(h.shape[0] * k, E))
        return y
    return jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(),
                                 out_specs=P(), check_rep=False))


def greedy(kind, steps=12):
    # a real autoregressive loop: each step routes the running state
    # through the MoE FFN and emits the argmax token (decode regime:
    # ONE live row per step, the smallest T the tile path ever sees)
    fn = step_fn(kind)
    toks, worst = [3], 0.0
    h = embed[3][None]
    for _ in range(steps):
        y = h + fn(h)
        logits = y @ head
        toks.append(int(jnp.argmax(logits[-1])))
        h = embed[toks[-1]][None] + 0.5 * y[-1:]
    return toks, np.asarray(fn(embed[:8]))


td, yd = greedy("dense")
tg, yg = greedy("dropless")
print(json.dumps({
    "dense": td, "dropless": tg,
    "max_diff": float(np.abs(yd - yg).max()),
    "x64": bool(jnp.zeros(()).dtype == jnp.float64),
}))
"""


@pytest.mark.slow
def test_float64_dropless_vs_dense_mixture_oracle():
    """At float64 the dropless grouped-GEMM decode path is the dense
    (no-drop) top-k mixture: token-identical greedy streams through a
    real decode loop and <= 1e-12 on raw FFN outputs — nothing CAN drop,
    so the only possible divergence is permutation arithmetic."""
    env = {key: v for key, v in os.environ.items()
           if not key.startswith("BLUEFOG_")
           and key not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_ENABLE_X64")}
    p = subprocess.run([sys.executable, "-c", _F64_ORACLE],
                       cwd=REPO, capture_output=True, text=True,
                       timeout=420, env=env)
    assert p.returncode == 0, p.stderr[-3000:]
    doc = json.loads(p.stdout.strip().splitlines()[-1])
    assert doc["x64"], doc
    assert doc["dense"] == doc["dropless"], doc
    assert doc["max_diff"] < 1e-12, doc


# ---------------------------------------------------------------------------
# Launcher surface
# ---------------------------------------------------------------------------

def test_launcher_serve_moe_env():
    from bluefog_tpu.run import launcher
    args = launcher.build_parser().parse_args(
        ["--serve", "--serve-moe", "8x2@2:4", "python", "x.py"])
    env = launcher._child_env(args)
    assert env["BLUEFOG_SERVE_MOE"] == "8x2@2:4"
    args = launcher.build_parser().parse_args(["--serve", "python", "x.py"])
    assert "BLUEFOG_SERVE_MOE" not in launcher._child_env(args)
