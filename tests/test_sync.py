"""Handle-semantics surface: synchronize / poll / barrier / hard_sync.

Reference anchor: ``bf.synchronize(handle)`` / ``bf.poll(handle)`` / the
handle manager (`/root/reference/bluefog/torch/mpi_ops.py:962-1005`).  JAX
arrays are the handles; ``hard_sync`` is the extra device-to-host barrier
this framework needs because some PJRT plugins report buffers ready at
dispatch time (see bf.hard_sync docstring).
"""
import jax
import jax.numpy as jnp
import numpy as np

import bluefog_tpu as bf


def test_synchronize_returns_value():
    x = jnp.arange(4.0)
    y = bf.synchronize(x * 2)
    np.testing.assert_allclose(np.asarray(y), [0, 2, 4, 6])


def test_poll_true_after_synchronize():
    x = jnp.arange(4.0) + 1
    bf.synchronize(x)
    assert bf.poll(x) is True


def test_barrier_runs():
    bf.barrier()


def test_hard_sync_passes_through_pytrees():
    tree = {"a": jnp.ones((3, 2)), "b": (jnp.zeros(()), [1.5, None])}
    out = bf.hard_sync(tree)
    assert out is tree
    np.testing.assert_allclose(np.asarray(out["a"]), np.ones((3, 2)))


def test_hard_sync_empty_and_scalar():
    assert bf.hard_sync(()) == ()
    s = jnp.float32(3.0)
    assert float(bf.hard_sync(s)) == 3.0
