"""Tensor parallelism: sharded layers == dense reference; composes with
gossip DP on a 2-D (rank, model) mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.parallel.tensor_parallel import (
    ColumnParallelDense, RowParallelDense, TPMlpBlock)

N = 8


def test_tp_mlp_matches_dense(cpu_devices):
    """A TP-sharded MLP forward equals the unsharded computation."""
    mesh = Mesh(np.array(cpu_devices[:4]), ("model",))
    B, Din, H, Dout = 2, 6, 8, 5
    x = jnp.asarray(np.random.default_rng(0).normal(size=(B, Din)), jnp.float32)

    block = TPMlpBlock(hidden=H, features=Dout, axis="model")

    def init_and_apply(xb):
        params = block.init(jax.random.key(0), xb)
        return block.apply(params, xb), jax.tree.map(lambda v: v[None], params)

    fn = jax.jit(jax.shard_map(
        init_and_apply, mesh=mesh, in_specs=P(),
        out_specs=(P(), P("model"))))
    y_tp, params_tp = fn(x)
    assert y_tp.shape == (B, Dout)

    # dense oracle: concatenate the column shards / stack the row shards
    w1 = np.concatenate(
        [np.asarray(params_tp["params"]["ColumnParallelDense_0"]["Dense_0"]
                    ["kernel"][i]) for i in range(4)], axis=1)
    b1 = np.concatenate(
        [np.asarray(params_tp["params"]["ColumnParallelDense_0"]["Dense_0"]
                    ["bias"][i]) for i in range(4)])
    w2 = np.concatenate(
        [np.asarray(params_tp["params"]["RowParallelDense_0"]["Dense_0"]
                    ["kernel"][i]) for i in range(4)], axis=0)
    b2 = np.asarray(params_tp["params"]["RowParallelDense_0"]["bias"][0])
    h = np.asarray(jax.nn.gelu(jnp.asarray(np.asarray(x) @ w1 + b1)))
    expected = h @ w2 + b2
    np.testing.assert_allclose(np.asarray(y_tp), expected, rtol=1e-5, atol=1e-5)


def test_gossip_dp_times_tp(cpu_devices):
    """2-D (rank x model) mesh: gossip-average weight shards over ranks while
    the model axis carries the TP psum — one training step runs and the
    rank-axis gossip drives shard consensus."""
    mesh = Mesh(np.array(cpu_devices).reshape(4, 2), ("rank", "model"))
    bf.init(devices=cpu_devices, nodes_per_machine=1)
    try:
        import bluefog_tpu.topology as tu
        from bluefog_tpu import schedule as sch
        topo = tu.RingGraph(4)
        sched = sch.compile_topology(topo, weighted=True)

        block = TPMlpBlock(hidden=8, features=4, axis="model")
        B = 2
        x = jnp.asarray(np.random.default_rng(1).normal(size=(4, B, 6)),
                        jnp.float32)
        y = jnp.zeros((4, B, 4), jnp.float32)

        def step(xb, yb, seed):
            params = block.init(jax.random.key(seed[0, 0]), xb[0])

            def loss_fn(p):
                return jnp.mean((block.apply(p, xb[0]) - yb[0]) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            params = optax.apply_updates(
                grads, jax.tree.map(lambda g: -0.1 * g, params))
            # gossip the (model-sharded) weights over the rank axis
            from bluefog_tpu import ops
            params = jax.tree.map(
                lambda w: ops.neighbor_allreduce(w, sched, axis="rank"),
                params)
            return jax.tree.map(lambda v: v[None], (loss, params))

        fn = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(P("rank"), P("rank"), P("rank")),
            out_specs=(P("rank"), (P("rank", "model")))))
        # per-rank seeds -> different initial shards; gossip mixes them
        loss, params = fn(x, y, jnp.arange(4, dtype=jnp.int32)[:, None])
        assert np.isfinite(np.asarray(loss)).all()
        for leaf in jax.tree.leaves(params):
            assert leaf.shape[0] == 4          # rank axis preserved
    finally:
        bf.shutdown()
