"""TF/Keras weight migration: round-trip + decentralized-training handoff.

The §2.3 on-ramp (reference: ``bluefog/tensorflow/mpi_ops.py:95-204`` binds
TF ops directly; here the weights migrate into the pytree world and every
strategy applies unchanged).
"""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import jax.numpy as jnp  # noqa: E402

import bluefog_tpu as bf  # noqa: E402
from bluefog_tpu import optimizers as bfopt  # noqa: E402
from bluefog_tpu import topology as tu  # noqa: E402
from bluefog_tpu.utils import tf_compat  # noqa: E402


def _model():
    tf.random.set_seed(0)
    return tf.keras.Sequential([
        tf.keras.Input(shape=(3,)),
        tf.keras.layers.Dense(4, activation="tanh", name="hidden"),
        tf.keras.layers.Dense(2, name="out"),
    ])


def test_keras_round_trip_is_exact():
    m = _model()
    tree = tf_compat.from_keras(m)
    # pathed nesting, flax-convention layouts (kernel [in, out] — no
    # transpose, unlike torch)
    assert tree["hidden"]["kernel"].shape == (3, 4)
    assert tree["out"]["bias"].shape == (2,)
    assert tf_compat.param_count(tree) == m.count_params()

    m2 = _model()
    tf_compat.to_keras(m2, tree)
    for a, b in zip(m.get_weights(), m2.get_weights()):
        np.testing.assert_array_equal(a, b)
    # and predictions agree
    x = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)
    np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_keras_shape_mismatch_and_missing_fail_loud():
    m = _model()
    tree = tf_compat.from_keras(m)
    bad = {**tree, "hidden": {**tree["hidden"],
                              "kernel": jnp.zeros((3, 5))}}
    with pytest.raises(ValueError, match="hidden/kernel"):
        tf_compat.to_keras(_model(), bad)
    del bad["hidden"]
    with pytest.raises(ValueError, match="missing"):
        tf_compat.to_keras(_model(), bad)


def test_variables_round_trip():
    v = [tf.Variable(np.arange(6, dtype=np.float32).reshape(2, 3),
                     name="scope/w"),
         tf.Variable(np.ones(3, dtype=np.float32), name="scope/b")]
    tree = tf_compat.from_variables(v)
    assert tree["scope"]["w"].shape == (2, 3)
    tree = {"scope": {"w": tree["scope"]["w"] * 2,
                      "b": tree["scope"]["b"] + 1}}
    tf_compat.to_variables(v, tree)
    np.testing.assert_array_equal(
        v[0].numpy(), 2 * np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_array_equal(v[1].numpy(), 2 * np.ones(3))


def test_keras_weights_train_decentralized(cpu_devices):
    """The handoff a reference TF user needs: Keras weights -> pytree ->
    a few CTA gossip steps on the mesh -> back into Keras, all ranks at
    consensus."""
    n = 8
    bf.init(devices=cpu_devices)
    bf.set_topology(tu.ExponentialTwoGraph(n), is_weighted=True)
    try:
        m = _model()
        params = tf_compat.from_keras(m)
        import optax

        strat = bfopt.DistributedAdaptWithCombineOptimizer(
            optax.sgd(0.05), communication_type="neighbor_allreduce")
        dist = bfopt.replicate(params, n)
        state = bfopt.init_distributed(strat, dist)

        def grad_fn(p, batch):
            import jax

            def loss(q):
                h = jnp.tanh(batch @ q["hidden"]["kernel"]
                             + q["hidden"]["bias"])
                y = h @ q["out"]["kernel"] + q["out"]["bias"]
                return jnp.mean(y ** 2)

            return jax.value_and_grad(loss)(p)

        step = bfopt.make_train_step(grad_fn, strat)
        batch = jnp.broadcast_to(
            jnp.linspace(-1, 1, 3 * 4).reshape(4, 3)[None], (n, 4, 3))
        import jax
        for _ in range(3):
            dist, state, loss = step(dist, state, batch)
            jax.block_until_ready(loss)

        rank0 = jax.tree.map(lambda x: np.asarray(x[0]), dist)
        tf_compat.to_keras(m, rank0)
        np.testing.assert_allclose(
            m.get_weights()[0], np.asarray(rank0["hidden"]["kernel"]),
            rtol=1e-6)
    finally:
        bf.shutdown()
