"""Timeline integration: real ops emit the expected activities.

Port of the reference's ``test/timeline_test.py:54-117``, which runs real
collectives with the timeline enabled and asserts the emitted JSON contains
the expected activity spans per tensor.  Here the timeline is enabled via
the same ``BLUEFOG_TIMELINE`` hook ``bf.init`` honors, one CTA train step
plus eager blocking ops run, and ``<prefix>.activities.json`` must contain:

* ``COMMUNICATE`` / ``ADAPT`` spans from the optimizer strategy's named
  scopes (trace-time host spans; the same names label the device trace);
* ``STATE_SYNC`` when a stateful step runs with ``state_sync=`` enabled;
* one per-op span per eager blocking call, named after the op.
"""
import json

import jax
import jax.numpy as jnp
import optax
import pytest

import bluefog_tpu as bf
from bluefog_tpu import optimizers as bfopt
from bluefog_tpu import topology as tu
from bluefog_tpu.utils import timeline as tl

N, D = 8, 4


@pytest.fixture
def ctx(cpu_devices):
    bf.init(devices=cpu_devices)
    bf.set_topology(tu.ExponentialTwoGraph(N), is_weighted=True)
    yield
    bf.shutdown()


def grad_fn(params, batch):
    loss = jnp.mean((params["w"] - batch) ** 2)
    return loss, jax.grad(lambda p: jnp.mean((p["w"] - batch) ** 2))(params)


def _load_events(path):
    with open(path) as f:
        doc = json.load(f)
    return doc["traceEvents"]


def test_cta_step_and_eager_ops_emit_activities(ctx, tmp_path, monkeypatch):
    prefix = str(tmp_path / "tl")
    monkeypatch.setenv("BLUEFOG_TIMELINE", prefix)
    # the exact hook bf.init runs when BLUEFOG_TIMELINE is set
    tl.maybe_start_from_env()
    try:
        strat = bfopt.DistributedAdaptWithCombineOptimizer(
            optax.sgd(0.05), communication_type="neighbor_allreduce")
        params = bfopt.replicate({"w": jnp.zeros((D,), jnp.float32)})
        state = bfopt.init_distributed(strat, params)
        step = bfopt.make_train_step(grad_fn, strat)
        batch = jnp.broadcast_to(
            jnp.arange(float(N))[:, None], (N, D)).astype(jnp.float32)
        params, state, loss = step(params, state, batch)
        jax.block_until_ready(loss)

        # eager blocking ops record one span per call, named after the op
        x = bf.shard_distributed(batch)
        bf.synchronize(bf.neighbor_allreduce(x))
        bf.synchronize(bf.allreduce(x))
        bf.synchronize(bf.broadcast(x, 0))
    finally:
        out = tl.stop_timeline()

    events = _load_events(out)
    names = {e["name"] for e in events}
    # the reference asserts per-op activity names in the artifact
    # (test/timeline_test.py:54-117); COMMUNICATE/ADAPT are its
    # MPI-op/optimizer span names
    assert "COMMUNICATE" in names, names
    assert "ADAPT" in names, names
    cats = {e.get("cat") for e in events}
    assert {"neighbor_allreduce", "allreduce", "broadcast"} <= cats, cats
    # spans are well-formed complete events
    for e in events:
        assert e["ph"] == "X" and e["dur"] >= 0


def test_stateful_step_emits_state_sync(ctx, tmp_path):
    prefix = str(tmp_path / "tl_sync")
    assert tl.start_timeline(prefix, with_device_trace=False)
    try:
        strat = bfopt.DistributedAdaptWithCombineOptimizer(
            optax.sgd(0.05), communication_type="neighbor_allreduce")

        def sgrad_fn(params, net_state, batch):
            loss = jnp.mean((params["w"] - batch) ** 2)
            g = jax.grad(lambda p: jnp.mean((p["w"] - batch) ** 2))(params)
            return loss, g, {"ema": 0.9 * net_state["ema"] + 0.1 * loss}

        params = bfopt.replicate({"w": jnp.zeros((D,), jnp.float32)})
        net_state = bfopt.replicate({"ema": jnp.zeros((), jnp.float32)})
        state = bfopt.init_distributed(strat, params)
        step = bfopt.make_stateful_train_step(
            sgrad_fn, strat, state_sync="neighbor")
        batch = jnp.broadcast_to(
            jnp.arange(float(N))[:, None], (N, D)).astype(jnp.float32)
        params, net_state, state, loss = step(params, net_state, state, batch)
        jax.block_until_ready(loss)
    finally:
        out = tl.stop_timeline()

    names = {e["name"] for e in _load_events(out)}
    assert "STATE_SYNC" in names, names
    assert "COMMUNICATE" in names and "ADAPT" in names, names


def test_timeline_off_means_no_artifact(ctx, tmp_path):
    """When the timeline is off the op API takes the zero-cost path (no
    spans buffered, stop returns None)."""
    x = bf.shard_distributed(jnp.ones((N, D), jnp.float32))
    bf.synchronize(bf.neighbor_allreduce(x))
    assert tl.stop_timeline() is None
