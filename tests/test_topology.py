"""Topology generator tests (model: reference test/torch_basics_test.py)."""
import numpy as np
import networkx as nx
import pytest

from bluefog_tpu import topology as tu


ALL_STATIC = [
    lambda n: tu.ExponentialTwoGraph(n),
    lambda n: tu.ExponentialGraph(n),
    lambda n: tu.SymmetricExponentialGraph(n),
    lambda n: tu.MeshGrid2DGraph(n),
    lambda n: tu.StarGraph(n),
    lambda n: tu.RingGraph(n),
    lambda n: tu.FullyConnectedGraph(n),
]


@pytest.mark.parametrize("gen", ALL_STATIC)
@pytest.mark.parametrize("size", [1, 2, 4, 8, 12])
def test_row_stochastic(gen, size):
    """Every generator emits a row-stochastic mixing matrix."""
    W = tu.to_weight_matrix(gen(size))
    np.testing.assert_allclose(W.sum(axis=1), np.ones(size), atol=1e-12)


@pytest.mark.parametrize("gen", ALL_STATIC)
@pytest.mark.parametrize("size", [4, 8])
def test_doubly_stochastic(gen, size):
    """The shipped static topologies are doubly stochastic (consensus-preserving)."""
    W = tu.to_weight_matrix(gen(size))
    np.testing.assert_allclose(W.sum(axis=0), np.ones(size), atol=1e-12)


def test_expo2_neighbors():
    """Exp2 on 8 nodes: rank r's out-neighbors are r+1, r+2, r+4 (mod 8).

    Mirrors reference test/torch_basics_test.py:130-144.
    """
    topo = tu.ExponentialTwoGraph(8)
    for r in range(8):
        assert tu.GetOutNeighbors(topo, r) == sorted((r + d) % 8 for d in (1, 2, 4))
        assert tu.GetInNeighbors(topo, r) == sorted((r - d) % 8 for d in (1, 2, 4))


def test_biring_neighbors():
    """Bidirectional ring: neighbors are r±1 (reference :146-170)."""
    topo = tu.RingGraph(8, connect_style=0)
    for r in range(8):
        assert tu.GetOutNeighbors(topo, r) == sorted({(r + 1) % 8, (r - 1) % 8})
    topo_l = tu.RingGraph(8, connect_style=1)
    assert tu.GetOutNeighbors(topo_l, 3) == [2]
    topo_r = tu.RingGraph(8, connect_style=2)
    assert tu.GetOutNeighbors(topo_r, 3) == [4]


def test_equivalence():
    assert tu.IsTopologyEquivalent(tu.ExponentialTwoGraph(8), tu.ExponentialTwoGraph(8))
    assert not tu.IsTopologyEquivalent(tu.ExponentialTwoGraph(8), tu.RingGraph(8))
    assert not tu.IsTopologyEquivalent(None, tu.RingGraph(8))
    assert not tu.IsTopologyEquivalent(tu.RingGraph(4), tu.RingGraph(8))


def test_regularity():
    assert tu.IsRegularGraph(tu.RingGraph(8))
    assert tu.IsRegularGraph(tu.FullyConnectedGraph(8))
    assert not tu.IsRegularGraph(tu.StarGraph(8))


def test_recv_send_weights_star():
    topo = tu.StarGraph(8, center_rank=0)
    sw, nbr = tu.GetRecvWeights(topo, 3)
    assert sw == pytest.approx(1 - 1 / 8)
    assert nbr == {0: pytest.approx(1 / 8)}
    sw0, nbr0 = tu.GetRecvWeights(topo, 0)
    assert sw0 == pytest.approx(1 / 8)
    assert set(nbr0) == set(range(1, 8))


def test_meshgrid_weights():
    """Hastings weights on a 2x2 grid: all inter-node weights 1/3."""
    W = tu.to_weight_matrix(tu.MeshGrid2DGraph(4))
    for i, j in [(0, 1), (0, 2), (1, 3), (2, 3)]:
        assert W[i, j] == pytest.approx(1 / 3)
        assert W[j, i] == pytest.approx(1 / 3)
    assert W[0, 3] == 0.0


def test_dynamic_one_peer_matches_recv():
    """send/recv lists across ranks are mutually consistent each step."""
    topo = tu.ExponentialTwoGraph(8)
    gens = [tu.GetDynamicOnePeerSendRecvRanks(topo, r) for r in range(8)]
    for _ in range(12):
        step = [next(g) for g in gens]
        sends = {r: step[r][0] for r in range(8)}
        recvs = {r: step[r][1] for r in range(8)}
        for r in range(8):
            (dst,) = sends[r]
            assert r in recvs[dst]
            for src in recvs[r]:
                assert sends[src] == [r]


def test_dynamic_one_peer_rejects_isolated_rank():
    """A rank with no non-self out-neighbors fails at construction, clearly."""
    topo = nx.DiGraph()
    topo.add_nodes_from(range(4))
    topo.add_edges_from([(0, 1), (1, 2), (2, 0)])  # rank 3 isolated
    for r in range(4):
        topo.add_edge(r, r)
    with pytest.raises(ValueError, match="out-neighbors"):
        tu.GetDynamicOnePeerSendRecvRanks(topo, 0)


def test_inner_outer_expo2_consistency():
    world, local = 16, 4
    gens = [tu.GetInnerOuterExpo2DynamicSendRecvRanks(world, local, r)
            for r in range(world)]
    for _ in range(10):
        step = [next(g) for g in gens]
        send = {r: step[r][0][0] for r in range(world)}
        recv = {r: step[r][1][0] for r in range(world)}
        # one-peer permutation: sends form a bijection and match recv claims
        assert sorted(send.values()) == list(range(world))
        for r in range(world):
            assert recv[send[r]] == r


def test_infer_source_from_destination():
    dsts = [[1, 2], [2], [0], [0, 1]]
    srcs = tu.InferSourceFromDestinationRanks(dsts)
    assert srcs == [[2, 3], [0, 3], [0, 1], []]
    assert tu.InferDestinationFromSourceRanks(srcs) == [sorted(d) for d in dsts]
