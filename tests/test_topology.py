"""Topology generator tests (model: reference test/torch_basics_test.py)."""
import numpy as np
import networkx as nx
import pytest

from bluefog_tpu import topology as tu


ALL_STATIC = [
    lambda n: tu.ExponentialTwoGraph(n),
    lambda n: tu.ExponentialGraph(n),
    lambda n: tu.SymmetricExponentialGraph(n),
    lambda n: tu.MeshGrid2DGraph(n),
    lambda n: tu.StarGraph(n),
    lambda n: tu.RingGraph(n),
    lambda n: tu.FullyConnectedGraph(n),
]


@pytest.mark.parametrize("gen", ALL_STATIC)
@pytest.mark.parametrize("size", [1, 2, 4, 8, 12])
def test_row_stochastic(gen, size):
    """Every generator emits a row-stochastic mixing matrix."""
    W = tu.to_weight_matrix(gen(size))
    np.testing.assert_allclose(W.sum(axis=1), np.ones(size), atol=1e-12)


@pytest.mark.parametrize("gen", ALL_STATIC)
@pytest.mark.parametrize("size", [4, 8])
def test_doubly_stochastic(gen, size):
    """The shipped static topologies are doubly stochastic (consensus-preserving)."""
    W = tu.to_weight_matrix(gen(size))
    np.testing.assert_allclose(W.sum(axis=0), np.ones(size), atol=1e-12)


def test_expo2_neighbors():
    """Exp2 on 8 nodes: rank r's out-neighbors are r+1, r+2, r+4 (mod 8).

    Mirrors reference test/torch_basics_test.py:130-144.
    """
    topo = tu.ExponentialTwoGraph(8)
    for r in range(8):
        assert tu.GetOutNeighbors(topo, r) == sorted((r + d) % 8 for d in (1, 2, 4))
        assert tu.GetInNeighbors(topo, r) == sorted((r - d) % 8 for d in (1, 2, 4))


def test_biring_neighbors():
    """Bidirectional ring: neighbors are r±1 (reference :146-170)."""
    topo = tu.RingGraph(8, connect_style=0)
    for r in range(8):
        assert tu.GetOutNeighbors(topo, r) == sorted({(r + 1) % 8, (r - 1) % 8})
    topo_l = tu.RingGraph(8, connect_style=1)
    assert tu.GetOutNeighbors(topo_l, 3) == [2]
    topo_r = tu.RingGraph(8, connect_style=2)
    assert tu.GetOutNeighbors(topo_r, 3) == [4]


def test_equivalence():
    assert tu.IsTopologyEquivalent(tu.ExponentialTwoGraph(8), tu.ExponentialTwoGraph(8))
    assert not tu.IsTopologyEquivalent(tu.ExponentialTwoGraph(8), tu.RingGraph(8))
    assert not tu.IsTopologyEquivalent(None, tu.RingGraph(8))
    assert not tu.IsTopologyEquivalent(tu.RingGraph(4), tu.RingGraph(8))


def test_regularity():
    assert tu.IsRegularGraph(tu.RingGraph(8))
    assert tu.IsRegularGraph(tu.FullyConnectedGraph(8))
    assert not tu.IsRegularGraph(tu.StarGraph(8))


def test_recv_send_weights_star():
    topo = tu.StarGraph(8, center_rank=0)
    sw, nbr = tu.GetRecvWeights(topo, 3)
    assert sw == pytest.approx(1 - 1 / 8)
    assert nbr == {0: pytest.approx(1 / 8)}
    sw0, nbr0 = tu.GetRecvWeights(topo, 0)
    assert sw0 == pytest.approx(1 / 8)
    assert set(nbr0) == set(range(1, 8))


def test_meshgrid_weights():
    """Hastings weights on a 2x2 grid: all inter-node weights 1/3."""
    W = tu.to_weight_matrix(tu.MeshGrid2DGraph(4))
    for i, j in [(0, 1), (0, 2), (1, 3), (2, 3)]:
        assert W[i, j] == pytest.approx(1 / 3)
        assert W[j, i] == pytest.approx(1 / 3)
    assert W[0, 3] == 0.0


def test_dynamic_one_peer_matches_recv():
    """send/recv lists across ranks are mutually consistent each step."""
    topo = tu.ExponentialTwoGraph(8)
    gens = [tu.GetDynamicOnePeerSendRecvRanks(topo, r) for r in range(8)]
    for _ in range(12):
        step = [next(g) for g in gens]
        sends = {r: step[r][0] for r in range(8)}
        recvs = {r: step[r][1] for r in range(8)}
        for r in range(8):
            (dst,) = sends[r]
            assert r in recvs[dst]
            for src in recvs[r]:
                assert sends[src] == [r]


def test_dynamic_one_peer_rejects_isolated_rank():
    """A rank with no non-self out-neighbors fails at construction, clearly."""
    topo = nx.DiGraph()
    topo.add_nodes_from(range(4))
    topo.add_edges_from([(0, 1), (1, 2), (2, 0)])  # rank 3 isolated
    for r in range(4):
        topo.add_edge(r, r)
    with pytest.raises(ValueError, match="out-neighbors"):
        tu.GetDynamicOnePeerSendRecvRanks(topo, 0)


def test_inner_outer_expo2_consistency():
    world, local = 16, 4
    gens = [tu.GetInnerOuterExpo2DynamicSendRecvRanks(world, local, r)
            for r in range(world)]
    for _ in range(10):
        step = [next(g) for g in gens]
        send = {r: step[r][0][0] for r in range(world)}
        recv = {r: step[r][1][0] for r in range(world)}
        # one-peer permutation: sends form a bijection and match recv claims
        assert sorted(send.values()) == list(range(world))
        for r in range(world):
            assert recv[send[r]] == r


def test_infer_source_from_destination():
    dsts = [[1, 2], [2], [0], [0, 1]]
    srcs = tu.InferSourceFromDestinationRanks(dsts)
    assert srcs == [[2, 3], [0, 3], [0, 1], []]
    assert tu.InferDestinationFromSourceRanks(srcs) == [sorted(d) for d in dsts]


# ---------------------------------------------------------------------------
# Spectral gap and the two-level (hierarchical) family
# ---------------------------------------------------------------------------

SPECTRAL_CASES = [
    ("exp2", lambda n: tu.ExponentialTwoGraph(n)),
    ("ring", lambda n: tu.RingGraph(n)),
    ("mesh", lambda n: tu.MeshGrid2DGraph(n)),
    ("star", lambda n: tu.StarGraph(n)),
    ("full", lambda n: tu.FullyConnectedGraph(n)),
]


def _eig_gap(W: np.ndarray) -> float:
    """Oracle: 1 - |lambda_2| via a direct dense eigendecomposition."""
    moduli = np.sort(np.abs(np.linalg.eigvals(W)))[::-1]
    return float(1.0 - moduli[1])


@pytest.mark.parametrize("name,gen", SPECTRAL_CASES)
@pytest.mark.parametrize("size", [4, 8, 12, 16])
def test_spectral_gap_matches_eigendecomposition(name, gen, size):
    """spectral_gap == 1 - |lambda_2| from numpy eig for every static family."""
    topo = gen(size)
    got = tu.spectral_gap(topo)
    want = _eig_gap(tu.to_weight_matrix(topo))
    assert abs(got - want) < 1e-8, (name, size, got, want)
    assert 0.0 <= got <= 1.0 + 1e-12


@pytest.mark.parametrize("intra", ["dense", "exp2", "ring"])
@pytest.mark.parametrize("inter", ["exp2", "ring", "full"])
def test_two_level_gap_matches_eigendecomposition(intra, inter):
    """Composed two-level matrices grade identically to the eig oracle."""
    topo = tu.TwoLevelGraph(4, 4, intra=intra, inter=inter)
    got = tu.spectral_gap(topo)
    want = _eig_gap(tu.to_weight_matrix(topo))
    assert abs(got - want) < 1e-8, (intra, inter, got, want)


def test_two_level_is_kron_of_levels():
    """W(TwoLevelGraph) == kron(W_machine, W_local), rank = machine*L + local."""
    M, L = 4, 2
    Wm = tu.to_weight_matrix(tu.ExponentialTwoGraph(M))
    W = tu.to_weight_matrix(tu.TwoLevelGraph(M, L))
    np.testing.assert_allclose(W, np.kron(Wm, np.full((L, L), 1.0 / L)),
                               atol=1e-12)
    # and compose_two_level is that product for arbitrary inputs
    np.testing.assert_allclose(tu.compose_two_level(Wm, L), W, atol=1e-12)


def test_two_level_dense_intra_gap_is_machine_gap():
    """With uniform intra-slice averaging (the pmean path) the composed
    consensus rate is exactly the cross-machine graph's: J/L contributes
    spectrum {1, 0}, so kron cannot create a larger second eigenvalue."""
    for M, L in [(4, 2), (8, 4), (16, 8)]:
        got = tu.spectral_gap(tu.TwoLevelGraph(M, L))
        want = tu.spectral_gap(tu.ExponentialTwoGraph(M))
        assert abs(got - want) < 1e-10, (M, L, got, want)


def test_two_level_doubly_stochastic():
    """Kron of doubly-stochastic levels stays doubly stochastic."""
    W = tu.to_weight_matrix(tu.TwoLevelGraph(4, 4, intra="exp2", inter="ring"))
    np.testing.assert_allclose(W.sum(axis=0), 1.0, atol=1e-12)
    np.testing.assert_allclose(W.sum(axis=1), 1.0, atol=1e-12)


def test_spectral_gap_circulant_fast_path_matches_dense():
    """The FFT fast path (circulant families) agrees with the dense
    fallback; non-circulant matrices (star) take the dense path and a
    deliberately perturbed-but-stochastic matrix still grades."""
    W = tu.to_weight_matrix(tu.ExponentialTwoGraph(32))
    assert tu._circulant_row(W) is not None
    assert abs(tu.spectral_gap(W) - _eig_gap(W)) < 1e-8
    Ws = tu.to_weight_matrix(tu.StarGraph(9))
    assert tu._circulant_row(Ws) is None
    assert abs(tu.spectral_gap(Ws) - _eig_gap(Ws)) < 1e-8


def test_spectral_gap_rejects_non_column_stochastic():
    W = np.array([[0.5, 0.6], [0.5, 0.6]])
    with pytest.raises(ValueError, match="column-stochastic"):
        tu.spectral_gap(W)


def test_spectral_gap_edge_sizes():
    assert tu.spectral_gap(np.ones((1, 1))) == 1.0
    # disconnected: two isolated self-loops -> |lambda_2| = 1, gap 0
    assert abs(tu.spectral_gap(np.eye(2))) < 1e-12


def test_two_level_rejects_unknown_families():
    with pytest.raises(ValueError, match="intra"):
        tu.TwoLevelGraph(4, 2, intra="bogus")
    with pytest.raises(ValueError, match="inter"):
        tu.TwoLevelGraph(4, 2, inter="bogus")
