"""torch state_dict <-> pytree round trip (migration path for reference users)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from bluefog_tpu.utils import torch_compat


def test_roundtrip():
    model = torch.nn.Sequential(
        torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 2))
    sd = model.state_dict()
    tree = torch_compat.from_torch(sd)
    assert set(tree.keys()) == {"0", "2"}
    assert tree["0"]["weight"].shape == (8, 4)
    back = torch_compat.to_torch(tree)
    assert set(back.keys()) == set(sd.keys())
    for k in sd:
        np.testing.assert_allclose(
            back[k].numpy(), sd[k].detach().numpy(), rtol=1e-6)


def test_dtype_override():
    import jax.numpy as jnp
    sd = {"w": torch.ones(3, 3, dtype=torch.float64)}
    tree = torch_compat.from_torch(sd, dtype=jnp.bfloat16)
    assert tree["w"].dtype == jnp.bfloat16


class TestLayoutHelpers:
    """The kernel-layout converters produce numerically identical layers:
    torch NCHW forward == flax NHWC forward through the converted weights
    (the whole point of the migration path, examples/torch_migration.py)."""

    def test_conv_kernel_matches_torch_conv(self):
        import jax.numpy as jnp
        from jax import lax
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 1, 8, 8)).astype(np.float32)    # NCHW
        conv = torch.nn.Conv2d(1, 3, 3)
        with torch.no_grad():
            ref = conv(torch.from_numpy(x)).numpy()             # [2,3,6,6]
        k = torch_compat.conv_kernel(conv.weight.detach().numpy())
        out = lax.conv_general_dilated(
            jnp.asarray(np.transpose(x, (0, 2, 3, 1))), k, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        out = out + jnp.asarray(conv.bias.detach().numpy())
        np.testing.assert_allclose(
            np.transpose(np.asarray(out), (0, 3, 1, 2)), ref,
            rtol=1e-4, atol=1e-5)

    def test_flatten_kernel_matches_torch_fc(self):
        """NCHW flattens (C,H,W), NHWC flattens (H,W,C): the fc-after-
        flatten kernel must reorder its input axis, not just transpose."""
        import jax.numpy as jnp
        rng = np.random.default_rng(1)
        c, h, w = 3, 4, 5
        feat = rng.normal(size=(2, c, h, w)).astype(np.float32)  # NCHW
        fc = torch.nn.Linear(c * h * w, 7)
        with torch.no_grad():
            ref = fc(torch.from_numpy(feat).flatten(1)).numpy()
        k = torch_compat.flatten_kernel(fc.weight.detach().numpy(),
                                        chw=(c, h, w))
        nhwc_flat = jnp.asarray(
            np.transpose(feat, (0, 2, 3, 1)).reshape(2, -1))
        out = nhwc_flat @ k + jnp.asarray(fc.bias.detach().numpy())
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_inverses_round_trip(self):
        rng = np.random.default_rng(2)
        conv_w = rng.normal(size=(6, 3, 3, 3)).astype(np.float32)
        lin_w = rng.normal(size=(7, 11)).astype(np.float32)
        fc_w = rng.normal(size=(7, 3 * 4 * 5)).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(torch_compat.conv_kernel_to_torch(
                torch_compat.conv_kernel(conv_w))), conv_w)
        np.testing.assert_array_equal(
            np.asarray(torch_compat.linear_kernel_to_torch(
                torch_compat.linear_kernel(lin_w))), lin_w)
        np.testing.assert_array_equal(
            np.asarray(torch_compat.flatten_kernel_to_torch(
                torch_compat.flatten_kernel(fc_w, chw=(3, 4, 5)),
                chw=(3, 4, 5))), fc_w)
