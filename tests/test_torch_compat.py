"""torch state_dict <-> pytree round trip (migration path for reference users)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from bluefog_tpu.utils import torch_compat


def test_roundtrip():
    model = torch.nn.Sequential(
        torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 2))
    sd = model.state_dict()
    tree = torch_compat.from_torch(sd)
    assert set(tree.keys()) == {"0", "2"}
    assert tree["0"]["weight"].shape == (8, 4)
    back = torch_compat.to_torch(tree)
    assert set(back.keys()) == set(sd.keys())
    for k in sd:
        np.testing.assert_allclose(
            back[k].numpy(), sd[k].detach().numpy(), rtol=1e-6)


def test_dtype_override():
    import jax.numpy as jnp
    sd = {"w": torch.ones(3, 3, dtype=torch.float64)}
    tree = torch_compat.from_torch(sd, dtype=jnp.bfloat16)
    assert tree["w"].dtype == jnp.bfloat16
