"""AOT-compile against an abstract TPU topology: the round-2 overlap proofs.

No TPU hardware is needed: ``jax.experimental.topologies.get_topology_desc``
builds an 8-device v5e mesh description and XLA's real TPU pipeline compiles
against it, so these tests assert properties of the *actual TPU schedule* —
async collective-permute pairs spanning compute (the overlap the reference
gets from its background thread + nonblocking MPI, ``operations.cc:453-520``),
fusion collapsing per-leaf permute chains, and the Pallas flash kernels
lowering through Mosaic.
"""
import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu import optimizers as bfopt
from bluefog_tpu import schedule as sch
from bluefog_tpu import topology as tu
from bluefog_tpu.ops import ring_attention
from bluefog_tpu.ops import ulysses as ops_ulysses

# compile-heavy: AOT-compiles real v5e TPU schedules (10-15 s each when
# the topology backend is available) — the full-tier overlap proofs
pytestmark = pytest.mark.slow

N = 8

# History: these flash-kernel lowerings used to xfail because the backward
# kernel's bool [QB, 1] -> [QB, Tk] lane-broadcast (the isneginf(lse) guard)
# lowered to a 'tpu.dynamic_gather' on vector<8x128xi1> that Mosaic cannot
# legalize.  ops/pallas_attention.py now broadcasts lse to the score shape
# as f32 BEFORE the -inf test (f32 lane-broadcasts legalize fine), so every
# Pallas kernel in the repo compiles clean for v5e — a regression here
# should go red, no xfail guard.


@pytest.fixture(scope="module")
def tpu_mesh():
    from jax.experimental import topologies
    try:
        td = topologies.get_topology_desc("v5e:2x4", platform="tpu")
    except Exception as e:          # no libtpu in this environment
        pytest.skip(f"TPU AOT topology unavailable: {e}")
    return Mesh(np.array(td.devices), ("rank",))


@pytest.fixture(scope="module")
def tpu_mesh_2d():
    from jax.experimental import topologies
    try:
        td = topologies.get_topology_desc("v5e:2x4", platform="tpu")
    except Exception as e:          # no libtpu in this environment
        pytest.skip(f"TPU AOT topology unavailable: {e}")
    return Mesh(np.array(td.devices).reshape(2, 4), ("machine", "local"))


def _sharded_sds(tree, mesh):
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=NamedSharding(mesh, P("rank"))), tree)


def _compile_cta(mesh, fuse, steps=2, dim=128):
    """Fused CTA train step (2-layer MLP, scan over steps) -> optimized HLO."""
    sched = sch.compile_topology(tu.ExponentialTwoGraph(N))
    strat = bfopt.adapt_with_combine(
        optax.sgd(0.01),
        bfopt.neighbor_communicator(sched, fuse=fuse))

    def grad_fn(params, batch):
        x, y = batch
        def loss(p):
            h = jnp.tanh(x @ p["w1"])
            return jnp.mean((h @ p["w2"] - y).astype(jnp.float32) ** 2)
        return jax.value_and_grad(loss)(params)

    def per_rank(params, state, batch):
        params, state, batch = jax.tree.map(
            lambda t: t[0], (params, state, batch))
        def body(carry, b):
            p, s = carry
            loss, grads = grad_fn(p, b)
            p, s = strat.update(grads, s, p)
            return (p, s), loss
        (params, state), losses = jax.lax.scan(
            body, (params, state), batch, length=steps)
        return jax.tree.map(lambda t: t[None], (params, state, losses))

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=mesh, in_specs=(P("rank"),) * 3,
        out_specs=(P("rank"),) * 3), donate_argnums=(0, 1))

    params = {"w1": jnp.zeros((N, dim, dim), jnp.bfloat16),
              "w2": jnp.zeros((N, dim, dim), jnp.bfloat16)}
    state0 = strat.init(jax.tree.map(lambda x: x[0], params))
    state = jax.tree.map(lambda x: jnp.broadcast_to(x, (N,) + x.shape), state0)
    batch = tuple(jnp.zeros((N, steps, 16, dim), jnp.bfloat16)
                  for _ in range(2))
    sds = _sharded_sds((params, state, batch), mesh)
    return fn.lower(*sds).compile().as_text()


def _op_lines(txt, opname):
    """Line numbers defining an op (`%x = ... opname(...)`), not uses of it."""
    pat = re.compile(r"= [^=]*\b" + opname + r"\(")
    return [i for i, l in enumerate(txt.splitlines()) if pat.search(l)]


def test_cta_gossip_is_async_and_overlapped(tpu_mesh):
    """The TPU schedule issues all gossip rounds as async start/done pairs
    and places real compute between them (overlap, SURVEY.md §7 hard-part 5)."""
    txt = _compile_cta(tpu_mesh, fuse=True)
    starts = _op_lines(txt, "collective-permute-start")
    dones = _op_lines(txt, "collective-permute-done")
    # Exp2(8) = 3 edge-colored rounds; fusion => one permute chain total,
    # and the rounds are disjoint permutations so XLA runs all 3 concurrently
    assert len(starts) == 3, txt.count("collective-permute")
    assert len(dones) == 3
    # overlap: compute (fused loops/matmuls) scheduled inside the
    # start..done window — communication is hidden behind it
    lines = txt.splitlines()
    window = lines[max(starts) + 1:min(dones)]
    compute = [l for l in window
               if re.search(r"= \S+ (fusion|dot|convolution)\(", l)]
    assert compute, "no compute scheduled between permute start and done"
    # the gossip buffer is the fused bf16 flat buffer, not per-leaf
    assert re.search(r"collective-permute-start[^\n]*bf16", "\n".join(
        lines[starts[0]:starts[0] + 1]))


def test_fusion_collapses_permute_chains(tpu_mesh):
    """fuse=True gossips one flat buffer per dtype: permute count equals the
    schedule's round count instead of rounds x leaves."""
    fused = _compile_cta(tpu_mesh, fuse=True)
    unfused = _compile_cta(tpu_mesh, fuse=False)
    n_fused = len(_op_lines(fused, "collective-permute-start"))
    n_unfused = len(_op_lines(unfused, "collective-permute-start"))
    assert n_fused == 3                      # rounds(Exp2(8)) == 3
    assert n_unfused == 6                    # rounds x 2 leaves
    assert fused.count("all-reduce") == 0    # gossip never falls back


def test_pallas_flash_kernels_lower_for_tpu(tpu_mesh):
    """ring_attention(use_pallas) fwd+bwd compiles through Mosaic for v5e —
    the kernels are real TPU programs, not only interpret-mode constructs."""
    B, T, H, D = 1, N * 512, 4, 64

    def loss(q, k, v):
        out = ring_attention(q, k, v, axis="rank", causal=True,
                             use_pallas=True, pallas_interpret=False)
        return jax.lax.psum(jnp.sum(out.astype(jnp.float32) ** 2), "rank")

    g = jax.value_and_grad(loss, argnums=(0, 1, 2))
    fn = jax.jit(jax.shard_map(
        g, mesh=tpu_mesh, in_specs=(P(None, "rank"),) * 3,
        out_specs=(P(), (P(None, "rank"),) * 3),
        check_vma=False))
    sds = tuple(jax.ShapeDtypeStruct(
        (B, T, H, D), jnp.bfloat16,
        sharding=NamedSharding(tpu_mesh, P(None, "rank"))) for _ in range(3))
    txt = fn.lower(*sds).compile().as_text()
    # one Mosaic custom call for the forward partial kernel, one for backward
    assert txt.count("tpu_custom_call") == 2
    # the ring rotation is ppermute (async on TPU), present in both passes
    assert len(_op_lines(txt, "collective-permute-start")) >= 2


def test_dynamic_one_peer_is_one_permute_per_step(tpu_mesh):
    """Dynamic one-peer gossip compiles to exactly ONE async permute per
    scanned step — communication constant in n (the table in
    docs/PERFORMANCE.md), with the per-step branch select never falling back
    to a gather/allreduce."""
    scheds = sch.compile_dynamic_schedules(
        lambda r: tu.GetDynamicOnePeerSendRecvRanks(
            tu.ExponentialTwoGraph(N), r), N)
    strat = bfopt.adapt_with_combine(
        optax.sgd(0.01), bfopt.neighbor_communicator(schedules=scheds))
    steps = len(scheds)

    def per_rank(params, state, batch):
        params, state, batch = jax.tree.map(
            lambda t: t[0], (params, state, batch))
        def body(carry, b):
            p, s = carry
            loss, grads = jax.value_and_grad(
                lambda q: jnp.mean((b @ q["w"]).astype(jnp.float32) ** 2))(p)
            p, s = strat.update(grads, s, p)
            return (p, s), loss
        (params, state), losses = jax.lax.scan(
            body, (params, state), batch, length=steps)
        return jax.tree.map(lambda t: t[None], (params, state, losses))

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),) * 3,
        out_specs=(P("rank"),) * 3))
    params = {"w": jnp.zeros((N, 128, 128), jnp.bfloat16)}
    state0 = strat.init(jax.tree.map(lambda x: x[0], params))
    state = jax.tree.map(lambda x: jnp.broadcast_to(x, (N,) + x.shape), state0)
    batch = jnp.zeros((N, steps, 16, 128), jnp.bfloat16)
    sds = _sharded_sds((params, state, batch), tpu_mesh)
    txt = fn.lower(*sds).compile().as_text()

    starts = _op_lines(txt, "collective-permute-start")
    # every dynamic step is a single permutation of the rank axis: the
    # scan body holds one async permute per branch (or one shared permute
    # with branch-selected source-target pairs), never more than one per
    # step of the period — and no branch degrades to all-gather/all-reduce
    assert 1 <= len(starts) <= steps, txt.count("collective-permute")
    # substring check catches the async -start forms too
    assert txt.count("all-gather") == 0
    assert txt.count("all-reduce") == 0


def test_hierarchical_lowering_splits_axes(tpu_mesh_2d):
    """hierarchical_neighbor_allreduce on the 2-D (machine x local) mesh:
    the intra-machine average lowers to an all-reduce whose replica groups
    stay within each machine's local axis, and the machine-level gossip is
    async permutes — psum rides ICI, gossip rides the cross-machine axis
    (reference: mpi_controller.cc:452-507 three-phase hierarchy)."""
    from bluefog_tpu.ops import collectives as C

    msched = sch.compile_topology(tu.RingGraph(2))

    def per_rank(x):
        x = x[0, 0]
        out = C.hierarchical_neighbor_allreduce(x, msched)
        return out[None, None]

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh_2d,
        in_specs=(P("machine", "local"),), out_specs=P("machine", "local")))
    x = jax.ShapeDtypeStruct(
        (2, 4, 256, 256), jnp.bfloat16,
        sharding=NamedSharding(tpu_mesh_2d, P("machine", "local")))
    txt = fn.lower(x).compile().as_text()

    ars = [l for l in txt.splitlines()
           if re.search(r"= \S+ all-reduce(-start)?\(", l)]
    assert ars, "intra-machine pmean must lower to an all-reduce"
    # replica groups of the local pmean partition within machines:
    # {0,1,2,3} and {4,5,6,7}, never mixing the two machines
    groups = re.findall(r"replica_groups=\{(.*?)\}", " ".join(ars))
    assert groups
    for g in groups:
        for grp in re.findall(r"\{([\d,]+)\}", "{" + g + "}"):
            members = sorted(int(v) for v in grp.split(","))
            assert members in ([0, 1, 2, 3], [4, 5, 6, 7]), ars
    assert _op_lines(txt, "collective-permute-start"), \
        "machine-level gossip must stay an async permute"


def test_broadcast_is_log_tree_no_reduction(tpu_mesh):
    """broadcast lowers to ceil(log2 n) async permutes and ZERO all-reduces
    on the TPU pipeline (the binomial tree, not the masked-psum formulation)."""
    from bluefog_tpu.ops import collectives as C

    def per_rank(x):
        return C.broadcast(x[0], 3)[None]

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),),
        out_specs=P("rank")))
    x = jax.ShapeDtypeStruct(
        (N, 1024, 1024), jnp.bfloat16,
        sharding=NamedSharding(tpu_mesh, P("rank")))
    txt = fn.lower(x).compile().as_text()
    assert len(_op_lines(txt, "collective-permute-start")) == 3  # log2(8)
    assert txt.count("all-reduce") == 0    # incl. async -start form


def test_int8_wire_shrinks_permute_payload(tpu_mesh):
    """wire="int8" really compresses the TPU wire: the gossip permutes carry
    s8 buffers (plus a 4-byte f32 scale), not bf16/f32 — 2-4x fewer bytes
    per edge in the compiled schedule."""
    sched = sch.compile_topology(tu.ExponentialTwoGraph(N))

    def per_rank(x):
        from bluefog_tpu.ops import collectives as C
        return C.neighbor_allreduce(x[0], sched, wire="int8")[None]

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),),
        out_specs=P("rank")))
    x = jax.ShapeDtypeStruct(
        (N, 1024, 1024), jnp.bfloat16,
        sharding=NamedSharding(tpu_mesh, P("rank")))
    txt = fn.lower(x).compile().as_text()
    starts = _op_lines(txt, "collective-permute-start")
    lines = txt.splitlines()
    payload = [l for l in starts if re.search(r"s8\[", lines[l])]
    # 3 Exp2 rounds x (payload + scale); at least the 3 payload permutes
    # must be s8, and no full-precision f32 payload permute remains
    assert len(payload) == 3, [lines[l] for l in starts]
    assert not any(re.search(r"f32\[\d{4,}", lines[l]) for l in starts)


def test_fp8_wire_shrinks_permute_payload(tpu_mesh):
    """wire="fp8" carries f8e4m3 buffers on the compiled v5e wire — the
    int8 byte footprint with floating relative precision; the barriers
    keep XLA from fusing the casts back into a full-width permute."""
    sched = sch.compile_topology(tu.ExponentialTwoGraph(N))

    def per_rank(x):
        from bluefog_tpu.ops import collectives as C
        return C.neighbor_allreduce(x[0], sched, wire="fp8")[None]

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),),
        out_specs=P("rank")))
    x = jax.ShapeDtypeStruct(
        (N, 1024, 1024), jnp.float32,
        sharding=NamedSharding(tpu_mesh, P("rank")))
    txt = fn.lower(x).compile().as_text()
    starts = _op_lines(txt, "collective-permute-start")
    lines = txt.splitlines()
    payload = [l for l in starts if re.search(r"f8e4m3", lines[l])]
    assert len(payload) == 3, [lines[l][:120] for l in starts]
    assert not any(re.search(r"f32\[\d{4,}", lines[l]) for l in starts)


def test_blocked_wire_payloads_stay_compressed(tpu_mesh):
    """The @B blocked quantizers keep the compiled v5e wire compressed:
    payload permutes are s8 / f8e4m3 in the padded [nb, B] layout with an
    f32 per-block scales vector alongside — the pad/reshape around the
    optimization barriers must not give XLA an excuse to ship full-width
    bytes."""
    sched = sch.compile_topology(tu.ExponentialTwoGraph(N))

    for wire, pat in (("int8@256", r"s8\["), ("fp8@256", r"f8e4m3")):
        def per_rank(x, wire=wire):
            from bluefog_tpu.ops import collectives as C
            return C.neighbor_allreduce(x[0], sched, wire=wire)[None]

        fn = jax.jit(jax.shard_map(
            per_rank, mesh=tpu_mesh, in_specs=(P("rank"),),
            out_specs=P("rank")))
        x = jax.ShapeDtypeStruct(
            (N, 1000, 1001), jnp.float32,       # NOT a multiple of 256
            sharding=NamedSharding(tpu_mesh, P("rank")))
        txt = fn.lower(x).compile().as_text()
        starts = _op_lines(txt, "collective-permute-start")
        lines = txt.splitlines()
        payload = [l for l in starts if re.search(pat, lines[l])]
        assert len(payload) == 3, (wire, [lines[l][:120] for l in starts])
        # the scales vector may permute in f32 (3912 blocks = 4 bytes
        # each); full-width payloads (>= 6 digits of f32) must not
        assert not any(re.search(r"f32\[\d{6,}", lines[l])
                       for l in starts), wire


def test_bf16_wire_halves_permute_payload(tpu_mesh):
    """wire="bf16" on f32 data really halves the TPU wire: the gossip
    permutes carry bf16 buffers.  Guarded by optimization barriers in
    neighbor_allreduce — without them XLA commutes the decode convert
    across the collective-permute and the wire silently reverts to f32
    (observed on the CPU backend's float normalization; the barrier makes
    the codec's placement non-negotiable on every backend)."""
    sched = sch.compile_topology(tu.ExponentialTwoGraph(N))

    def per_rank(x):
        from bluefog_tpu.ops import collectives as C
        return C.neighbor_allreduce(x[0], sched, wire="bf16")[None]

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),),
        out_specs=P("rank")))
    x = jax.ShapeDtypeStruct(
        (N, 1024, 1024), jnp.float32,
        sharding=NamedSharding(tpu_mesh, P("rank")))
    txt = fn.lower(x).compile().as_text()
    starts = _op_lines(txt, "collective-permute-start")
    lines = txt.splitlines()
    payload = [l for l in starts if re.search(r"bf16\[", lines[l])]
    assert len(payload) == 3, [lines[l] for l in starts]    # 3 Exp2 rounds
    assert not any(re.search(r"f32\[\d{4,}", lines[l]) for l in starts)


def test_ulysses_kernels_lower_for_tpu(tpu_mesh):
    """ulysses_attention(use_pallas) fwd+bwd compiles through Mosaic for
    v5e, with the head/sequence re-shard lowering to all-to-all — the
    second SP mode is a real TPU program too."""
    # T and block_q sized to the backward kernel's VMEM budget: ulysses
    # holds the FULL sequence locally (scores [block_q, T] on stack), unlike
    # ring whose K/V chunks shrink with the mesh
    B, T, H, D = 1, N * 256, 8, 64

    def loss(q, k, v):
        out = ops_ulysses.ulysses_attention(
            q, k, v, axis="rank", causal=True, use_pallas=True,
            pallas_block_q=256, pallas_interpret=False)
        return jax.lax.psum(jnp.sum(out.astype(jnp.float32) ** 2), "rank")

    g = jax.value_and_grad(loss, argnums=(0, 1, 2))
    fn = jax.jit(jax.shard_map(
        g, mesh=tpu_mesh, in_specs=(P(None, "rank"),) * 3,
        out_specs=(P(), (P(None, "rank"),) * 3),
        check_vma=False))
    sds = tuple(jax.ShapeDtypeStruct(
        (B, T, H, D), jnp.bfloat16,
        sharding=NamedSharding(tpu_mesh, P(None, "rank"))) for _ in range(3))
    txt = fn.lower(*sds).compile().as_text()
    assert txt.count("tpu_custom_call") == 2      # fwd + bwd Mosaic kernels
    assert "all-to-all" in txt                    # the head/seq re-shard


def test_interleaved_pipeline_lowers_one_ring_permute(tpu_mesh):
    """The interleaved schedule's compiled v5e program carries exactly ONE
    async ring permute in the scanned tick body — per-tick comm is O(1)
    regardless of the chunk count V, and the ring includes the S-1 -> 0
    wrap that advances the chunk index."""
    from bluefog_tpu.parallel.pipeline import pipeline_interleaved_apply

    V, D = 2, 64

    def per_rank(chunks, mbs):
        chunks, mbs = jax.tree.map(lambda t: t[0], (chunks, mbs))
        out = pipeline_interleaved_apply(
            lambda p, x: jnp.tanh(x @ p), chunks, mbs, axis="rank")
        return out[None]

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"), P(None)),
        out_specs=P("rank")))
    sds = (jax.ShapeDtypeStruct(
               (N, V, D, D), jnp.bfloat16,
               sharding=NamedSharding(tpu_mesh, P("rank"))),
           jax.ShapeDtypeStruct(
               (1, N, 4, D), jnp.bfloat16,
               sharding=NamedSharding(tpu_mesh, P(None))))
    txt = fn.lower(*sds).compile().as_text()
    starts = _op_lines(txt, "collective-permute-start") \
        + _op_lines(txt, "collective-permute")
    assert len(starts) == 1, len(starts)
    lines = txt.splitlines()
    assert re.search(r"\{7,0\}", lines[starts[0]]), lines[starts[0]]


def test_strategy_comm_patterns_on_tpu_schedule(tpu_mesh):
    """Every strategy's cross-chip traffic, pinned: the compiled v5e step
    carries exactly the collectives the design promises (counts + payload
    dtypes).  Guards the whole optimizer surface against a silent comm
    regression (e.g. a fusion change splitting the permute chain, or a
    codec upcast like the bf16-wire bug this round)."""
    sched = sch.compile_topology(tu.ExponentialTwoGraph(N), weighted=True)
    dyn = sch.compile_dynamic_schedules(
        lambda r: tu.GetDynamicOnePeerSendRecvRanks(
            tu.ExponentialTwoGraph(N), r), N)
    opt = lambda: optax.sgd(0.05, momentum=0.9)
    # strategy -> (async permute-starts in text, all-reduce count)
    cases = {
        "allreduce": (bfopt.gradient_allreduce(opt()), 0, 1),
        "cta": (bfopt.adapt_with_combine(
            opt(), bfopt.neighbor_communicator(sched)), 3, 0),
        "atc": (bfopt.adapt_then_combine(
            opt(), bfopt.neighbor_communicator(sched)), 3, 0),
        # text carries every lax.switch branch (one executes per step)
        "dynamic": (bfopt.adapt_with_combine(
            opt(), bfopt.neighbor_communicator(schedules=dyn)), 3, 0),
        "win_put": (bfopt.win_put_optimizer(opt(), sched), 3, 0),
        "push_sum": (bfopt.push_sum(opt(), sched), 6, 0),   # value + P lane
        "choco": (bfopt.choco_gossip(opt(), sched), 6, 0),  # diff + zero-self
    }
    dim = 64

    def grad_fn(params, batch):
        x, y = batch

        def loss(p):
            return jnp.mean((jnp.tanh(x @ p["w"]) - y) ** 2)

        return jax.value_and_grad(loss)(params)

    for name, (strat, n_permute, n_allreduce) in cases.items():
        def per_rank(params, state, batch, strat=strat):
            params, state, batch = jax.tree.map(
                lambda t: t[0], (params, state, batch))
            _, grads = grad_fn(params, batch)
            params, state = strat.update(grads, state, params)
            return jax.tree.map(lambda t: t[None], (params, state))

        fn = jax.jit(jax.shard_map(
            per_rank, mesh=tpu_mesh, in_specs=(P("rank"),) * 3,
            out_specs=(P("rank"),) * 2))
        params = {"w": jnp.zeros((N, dim, dim), jnp.float32)}
        state0 = strat.init(jax.tree.map(lambda x: x[0], params))
        state = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (N,) + x.shape), state0)
        batch = tuple(jnp.zeros((N, 8, dim), jnp.float32) for _ in range(2))
        sds = _sharded_sds((params, state, batch), tpu_mesh)
        txt = fn.lower(*sds).compile().as_text()
        starts = (_op_lines(txt, "collective-permute-start")
                  + _op_lines(txt, "collective-permute"))
        ars = (_op_lines(txt, "all-reduce-start")
               + _op_lines(txt, "all-reduce"))
        assert len(starts) == n_permute, (name, len(starts), n_permute)
        assert len(ars) == n_allreduce, (name, len(ars), n_allreduce)
        if name == "choco":       # int8 wire: s8 payloads, none full-width
            lines = txt.splitlines()
            assert sum(bool(re.search(r"s8\[", lines[i]))
                       for i in starts) >= 3, name
            assert not any(re.search(r"f32\[\d{4,}", lines[i])
                           for i in starts), name


def test_flagship_resnet_gossip_step_tpu_schedule(tpu_mesh):
    """The headline bench path (ResNet + neighbor-allreduce CTA, the shape
    bench.py builds) compiles for v5e with bf16 convolutions feeding the
    MXU and the gossip as async fused permutes — the TPU schedule of the
    graded benchmark, proven without hardware."""
    from bluefog_tpu import models

    model = models.ResNet18(num_classes=10, num_filters=16)
    sched = sch.compile_topology(tu.ExponentialTwoGraph(N), weighted=True)
    strat = bfopt.adapt_with_combine(
        optax.sgd(0.1, momentum=0.9), bfopt.neighbor_communicator(sched))

    x0 = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x0, train=False)
    tstate = {"params": variables["params"], "bs": variables["batch_stats"]}

    def grad_fn(ts, batch):
        images, labels = batch

        def loss(p):
            logits, upd = model.apply(
                {"params": p, "batch_stats": ts["bs"]}, images,
                train=True, mutable=["batch_stats"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean(), upd["batch_stats"]

        (l, _), g = jax.value_and_grad(loss, has_aux=True)(ts["params"])
        return l, {"params": g, "bs": jax.tree.map(jnp.zeros_like, ts["bs"])}

    def per_rank(params, state, batch):
        params, state, batch = jax.tree.map(
            lambda t: t[0], (params, state, batch))
        loss, grads = grad_fn(params, batch)
        params, state = strat.update(grads, state, params)
        return jax.tree.map(lambda t: t[None], (params, state, loss))

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),) * 3,
        out_specs=(P("rank"),) * 3), donate_argnums=(0, 1))

    dist = jax.tree.map(lambda x: jnp.broadcast_to(x, (N,) + x.shape), tstate)
    state0 = strat.init(tstate)
    dstate = jax.tree.map(lambda x: jnp.broadcast_to(x, (N,) + x.shape),
                          state0)
    batch = (jnp.zeros((N, 2, 32, 32, 3), jnp.float32),
             jnp.zeros((N, 2), jnp.int32))
    sds = _sharded_sds((dist, dstate, batch), tpu_mesh)
    txt = fn.lower(*sds).compile().as_text()

    # gossip: fused per-dtype buffers -> async permute rounds, no allreduce
    starts = _op_lines(txt, "collective-permute-start")
    assert len(starts) == 3, len(starts)          # Exp2(8) edge colors
    assert not _op_lines(txt, "all-reduce") and \
        not _op_lines(txt, "all-reduce-start")
    # MXU path: the conv stack runs in bf16 (model default; a few f32-edge
    # gradient convs at the f32 stem input/head boundary are expected)
    lines = txt.splitlines()
    convs = [lines[i] for i in _op_lines(txt, "convolution")]
    assert convs, "no convolution instructions in the compiled step"
    bf16_convs = sum("bf16" in c for c in convs)
    assert bf16_convs >= 0.7 * len(convs), (bf16_convs, len(convs))
    assert not any("f64" in c for c in convs)
    # overlap: real compute is scheduled inside the permute start..done span
    dones = _op_lines(txt, "collective-permute-done")
    window = lines[max(starts) + 1:min(dones)]
    assert any(re.search(r"= \S+ (fusion|convolution|dot)\(", l)
               for l in window), "gossip not overlapped with compute"


def test_zero_lowering_is_reduce_scatter_all_gather(tpu_mesh):
    """The ZeRO-1 train step compiles to reduce-scatter + all-gather with no
    gradient all-reduce: each chip's optimizer state is the 1/n shard, and
    the collectives are async on the TPU schedule."""
    strat = bfopt.zero_gradient_allreduce(optax.adam(1e-3), axis_size=N)
    dim = 128

    def grad_fn(params, batch):
        x, y = batch
        def loss(p):
            h = jnp.tanh(x @ p["w1"])
            return jnp.mean((h @ p["w2"] - y).astype(jnp.float32) ** 2)
        return jax.value_and_grad(loss)(params)

    def per_rank(params, state, batch):
        params, state, batch = jax.tree.map(
            lambda t: t[0], (params, state, batch))
        loss, grads = grad_fn(params, batch)
        params, state = strat.update(grads, state, params)
        return jax.tree.map(lambda t: t[None], (params, state, loss))

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),) * 3,
        out_specs=(P("rank"),) * 3), donate_argnums=(0, 1))

    params = {"w1": jnp.zeros((N, dim, dim), jnp.bfloat16),
              "w2": jnp.zeros((N, dim, dim), jnp.bfloat16)}
    state0 = strat.init(jax.tree.map(lambda x: x[0], params))
    state = jax.tree.map(lambda x: jnp.broadcast_to(x, (N,) + x.shape), state0)
    batch = tuple(jnp.zeros((N, 16, dim), jnp.bfloat16) for _ in range(2))
    sds = _sharded_sds((params, state, batch), tpu_mesh)
    txt = fn.lower(*sds).compile().as_text()

    # ZeRO memory property: adam mu/nu enter and leave the program
    # shard-sized (dim*dim*2/N = 4096 elements per dtype bucket, bf16 to
    # match the params), never at the full 32768
    entry = txt.splitlines()[0]
    assert entry.count("bf16[1,4096]") >= 4, entry      # mu + nu in and out
    assert "bf16[1,32768]" not in entry
    # ZeRO dataflow: exactly one reduction of the fused grad buffer (XLA may
    # keep the StableHLO reduce_scatter or decompose it to all-reduce +
    # slice — both carry the fused 32768 bucket once) ...
    reductions = (_op_lines(txt, "reduce-scatter") +
                  _op_lines(txt, "reduce-scatter-start") +
                  _op_lines(txt, "all-reduce") +
                  _op_lines(txt, "all-reduce-start"))
    assert len(reductions) == 1, reductions
    # ... and one all-gather reassembling the updated params
    gathers = (_op_lines(txt, "all-gather") +
               _op_lines(txt, "all-gather-start"))
    assert len(gathers) == 1, gathers
    lines = txt.splitlines()
    assert re.search(r"bf16\[32768\]", lines[gathers[0]])


def test_zigzag_ring_lowers_with_conditional_skip(tpu_mesh):
    """The balanced (zigzag) causal ring compiles for v5e: the three chunk-
    pair partial sites lower through Mosaic, and the i>=s / s>=i visibility
    predicates become real HLO conditionals — devices skip fully-masked
    pairs at runtime instead of computing masked scores."""
    B, T, H, D = 1, N * 256, 4, 64      # per-device block 256 = 2 chunks

    def f(q, k, v):
        return ring_attention(q, k, v, axis="rank", causal=True,
                              layout="zigzag", use_pallas=True,
                              pallas_block_q=128, pallas_interpret=False)

    fn = jax.jit(jax.shard_map(
        f, mesh=tpu_mesh, in_specs=(P(None, "rank"),) * 3,
        out_specs=P(None, "rank"), check_vma=False))
    sds = tuple(jax.ShapeDtypeStruct(
        (B, T, H, D), jnp.bfloat16,
        sharding=NamedSharding(tpu_mesh, P(None, "rank"))) for _ in range(3))
    txt = fn.lower(*sds).compile().as_text()
    assert txt.count("tpu_custom_call") == 3     # lo x lo, hi x lo, hi x hi
    assert "conditional" in txt                  # the visibility skips


def test_zigzag_backward_lowers_through_mosaic(tpu_mesh):
    """grad(zigzag+pallas) compiles for v5e through the dedicated kernel
    backward: 3 forward + 3 backward Mosaic call sites, no dense [C, Tk]
    score matmul in HBM in either direction."""
    B, T, H, D = 1, N * 256, 4, 64

    def loss(q, k, v):
        out = ring_attention(q, k, v, axis="rank", causal=True,
                             layout="zigzag", use_pallas=True,
                             pallas_block_q=128, pallas_interpret=False)
        return jax.lax.psum(jnp.sum(out.astype(jnp.float32) ** 2), "rank")

    g = jax.value_and_grad(loss, argnums=(0, 1, 2))
    fn = jax.jit(jax.shard_map(
        g, mesh=tpu_mesh, in_specs=(P(None, "rank"),) * 3,
        out_specs=(P(), (P(None, "rank"),) * 3),
        check_vma=False))
    sds = tuple(jax.ShapeDtypeStruct(
        (B, T, H, D), jnp.bfloat16,
        sharding=NamedSharding(tpu_mesh, P(None, "rank"))) for _ in range(3))
    txt = fn.lower(*sds).compile().as_text()
    assert txt.count("tpu_custom_call") == 6


def test_choco_step_carries_int8_diffs_on_wire(tpu_mesh):
    """The CHOCO train step's permutes carry s8 payloads (the compressed
    DIFFERENCES) — no full-precision f32/bf16 parameter buffer crosses the
    wire, and the error-feedback state stays device-local."""
    sched = sch.compile_topology(tu.ExponentialTwoGraph(N), weighted=True)
    strat = bfopt.choco_gossip(optax.sgd(0.01), sched, wire="int8")
    dim = 128

    def per_rank(params, state, batch):
        params, state, batch = jax.tree.map(
            lambda t: t[0], (params, state, batch))
        loss, grads = jax.value_and_grad(
            lambda p: jnp.mean((batch @ p["w"]).astype(jnp.float32) ** 2))(
                params)
        params, state = strat.update(grads, state, params)
        return jax.tree.map(lambda t: t[None], (params, state, loss))

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),) * 3,
        out_specs=(P("rank"),) * 3), donate_argnums=(0, 1))
    params = {"w": jnp.zeros((N, dim, dim), jnp.float32)}
    state0 = strat.init(jax.tree.map(lambda x: x[0], params))
    state = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (N,) + x.shape), state0)
    batch = jnp.zeros((N, 16, dim), jnp.float32)
    sds = _sharded_sds((params, state, batch), tpu_mesh)
    txt = fn.lower(*sds).compile().as_text()

    starts = _op_lines(txt, "collective-permute-start")
    lines = txt.splitlines()
    payloads = [l for l in starts if re.search(r"s8\[", lines[l])]
    # 3 Exp2 rounds of (s8 payload + f32 scalar scale); every large buffer
    # on the wire is s8 — f32 permutes may only carry the scalar scale
    assert len(payloads) == 3, [lines[l][:100] for l in starts]
    assert not any(re.search(r"f32\[\d{3,}", lines[l]) for l in starts)


def test_win_put_wire_compresses_tpu_payload(tpu_mesh):
    """The window delivery path shares the codec-pinned permute helper:
    win_put(wire="bf16") on f32 windows carries bf16 permute payloads in
    the compiled v5e schedule — never full-width f32 (round-4 feature;
    the shared _wire_ppermute keeps the barrier subtlety in one place)."""
    from bluefog_tpu.ops import windows as wops

    sched = sch.compile_topology(tu.ExponentialTwoGraph(N))

    def per_rank(x):
        w = wops.win_create(x[0], sched)
        w = wops.win_put(w, x[0], sched, axis="rank", wire="bf16")
        return w.recv[None]

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),),
        out_specs=P("rank")))
    x = jax.ShapeDtypeStruct(
        (N, 1024, 1024), jnp.float32,
        sharding=NamedSharding(tpu_mesh, P("rank")))
    txt = fn.lower(x).compile().as_text()
    starts = _op_lines(txt, "collective-permute-start")
    lines = txt.splitlines()
    payload = [l for l in starts if re.search(r"bf16\[", lines[l])]
    assert len(payload) == 3, [lines[l] for l in starts]    # 3 Exp2 rounds
    assert not any(re.search(r"f32\[\d{4,}", lines[l]) for l in starts)


# Unlike the flash kernels' bool-mask gather (the xfail above), the
# grouped MoE kernel's scalar-prefetch index maps (weight block chosen by
# the prefetched tile_eid vector) legalize cleanly through this Mosaic —
# verified passing, so no xfail guard: a regression here should go red.
def test_grouped_moe_kernel_lowers_for_tpu(tpu_mesh):
    """The dropless grouped-GEMM Pallas kernel (ops/pallas_moe.py) fwd+bwd
    compiles through Mosaic for v5e: the scalar-prefetched ``tile_eid``
    drives the per-tile expert weight BlockSpec index maps, so expert
    weights stream from HBM tile-by-tile instead of a gathered
    ``w[tile_eid]`` copy materializing in full.  Compiled replicated over
    the AOT mesh — no collectives, same local program one chip runs."""
    from bluefog_tpu.ops.pallas_moe import grouped_ffn_pallas

    E_, G, tile, D, F = 4, 8, 128, 128, 256

    def loss(xt, w1, w2, eid):
        out = grouped_ffn_pallas(xt, eid, w1, w2, interpret=False)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def per_rank(xt, w1, w2, eid):
        xt, w1, w2, eid = jax.tree.map(lambda t: t[0], (xt, w1, w2, eid))
        l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(xt, w1, w2, eid)
        return jax.tree.map(lambda t: t[None], (l, g))

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),) * 4,
        out_specs=P("rank"), check_vma=False))
    sds = (jax.ShapeDtypeStruct((N, G, tile, D), jnp.float32,
                                sharding=NamedSharding(tpu_mesh, P("rank"))),
           jax.ShapeDtypeStruct((N, E_, D, F), jnp.float32,
                                sharding=NamedSharding(tpu_mesh, P("rank"))),
           jax.ShapeDtypeStruct((N, E_, F, D), jnp.float32,
                                sharding=NamedSharding(tpu_mesh, P("rank"))),
           jax.ShapeDtypeStruct((N, G), jnp.int32,
                                sharding=NamedSharding(tpu_mesh, P("rank"))))
    txt = fn.lower(*sds).compile().as_text()
    # the forward grouped GEMM is a Mosaic program (backward is XLA
    # scatter-adds by design — see pallas_moe._grouped_bwd)
    assert txt.count("tpu_custom_call") >= 1
    # and no dense [G*tile, E*F] gathered-weight intermediate materializes
    assert f"{G * tile},{E_ * F}" not in txt.replace(" ", "")


def test_flash_decode_kernel_lowers_for_tpu(tpu_mesh):
    """The paged flash-decode kernel (ops/pallas_decode.py) compiles through
    Mosaic for v5e on its most demanding configuration: int8 KV pages with
    fused per-token dequant, GQA folding, and the scalar-prefetched
    slot/prefix page indirection driving the KV BlockSpec index maps.
    Compiled replicated over the AOT mesh — no collectives, the same local
    program the serving hot path runs on one chip."""
    from bluefog_tpu.ops import pallas_decode as pd

    S, ROWS, H, Hkv, L, Dh = 8, 16, 8, 4, 1024, 128

    def per_rank(q, kl, vl, ksc, vsc, slots, lens, pslots, plens):
        (q, kl, vl, ksc, vsc, slots, lens, pslots, plens) = jax.tree.map(
            lambda t: t[0],
            (q, kl, vl, ksc, vsc, slots, lens, pslots, plens))
        out = pd.flash_attend_rows(
            q, kl, vl, slots, lens, k_scale=ksc, v_scale=vsc,
            prefix_slots=pslots, prefix_lens=plens, block_k=128,
            interpret=False)
        return out[None]

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"),) * 9,
        out_specs=P("rank"), check_vma=False))
    sh = NamedSharding(tpu_mesh, P("rank"))
    sds = (jax.ShapeDtypeStruct((N, S, H, Dh), jnp.bfloat16, sharding=sh),
           jax.ShapeDtypeStruct((N, ROWS, Hkv, L, Dh), jnp.int8, sharding=sh),
           jax.ShapeDtypeStruct((N, ROWS, Hkv, L, Dh), jnp.int8, sharding=sh),
           jax.ShapeDtypeStruct((N, ROWS, Hkv, L), jnp.float32, sharding=sh),
           jax.ShapeDtypeStruct((N, ROWS, Hkv, L), jnp.float32, sharding=sh),
           jax.ShapeDtypeStruct((N, S), jnp.int32, sharding=sh),
           jax.ShapeDtypeStruct((N, S), jnp.int32, sharding=sh),
           jax.ShapeDtypeStruct((N, S), jnp.int32, sharding=sh),
           jax.ShapeDtypeStruct((N, S), jnp.int32, sharding=sh))
    txt = fn.lower(*sds).compile().as_text()
    assert txt.count("tpu_custom_call") >= 1
    # paged reads: no [S, L] x heads dense gathered-KV copy materializes
    # at full width — the kernel streams (1, 1, block_k, Dh) pages
    assert f"f32[{S},{Hkv},{L},{Dh}]" not in txt.replace(" ", "")


@pytest.mark.parametrize("scan_layers,remat", [
    (False, False),       # stage-0 lm_bench_pallas default (pre-scan era)
    (True, False),        # lm_bench default: scan_layers on
    (True, True),         # stage-1 lm_bench_long_pallas: scan + remat
])
def test_single_device_lm_pallas_lowers_for_tpu(tpu_mesh, scan_layers,
                                                remat):
    """The battery's Pallas LM rows (tools/lm_bench.py on ONE chip:
    RingTransformerLM with axis=None + use_pallas, scanned and/or
    rematerialized) fwd+bwd compile through Mosaic for v5e — proven here
    so the first real-hardware run of local_flash_attention cannot die
    on a lowering bug mid-window.  Compiled replicated over the AOT
    mesh: no collectives, same local program a single chip runs."""
    from bluefog_tpu import models

    T = 1024
    lm = models.RingTransformerLM(
        vocab_size=128, num_layers=2, num_heads=4, d_model=128,
        max_seq_len=T, axis=None, dtype=jnp.bfloat16, rope=True,
        use_pallas=True, pallas_interpret=False,
        scan_layers=scan_layers, remat=remat)
    # init executes eagerly on the host CPU: use the dense clone (the
    # attention has no params, so the tree is identical) — the pallas lm
    # itself is only traced/lowered, never run here
    params = lm.clone(use_pallas=False).init(
        jax.random.key(0), jnp.zeros((1, T), jnp.int32))

    def loss_fn(p, tokens):
        logits = lm.apply(p, tokens, positions=jnp.arange(T))
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    # per-rank shard_map (leading [N] axis, the _sharded_sds pattern) pins
    # the AOT mesh as the lowering target — a bare jit with replicated
    # shardings falls back to the CPU backend and Pallas then refuses
    # interpret=False.  No collectives: each rank runs the same local
    # program a single chip would.  check_vma off: the local kernel's
    # scalar offsets are unvarying (axis=None) while q/k/v vary.
    def per_rank(p, tokens):
        p, tokens = jax.tree.map(lambda t: t[0], (p, tokens))
        loss, grads = jax.value_and_grad(loss_fn)(p, tokens)
        return jax.tree.map(lambda t: t[None], (loss, grads))

    fn = jax.jit(jax.shard_map(
        per_rank, mesh=tpu_mesh, in_specs=(P("rank"), P("rank")),
        out_specs=P("rank"), check_vma=False))
    params_N = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (N,) + x.shape), params)
    tokens_N = jnp.zeros((N, 1, T), jnp.int32)
    sds = _sharded_sds((params_N, tokens_N), tpu_mesh)
    txt = fn.lower(*sds).compile().as_text()
    # forward partial kernel + blockwise backward kernel reach Mosaic
    # (>=: XLA may or may not dedupe the per-layer instances)
    assert txt.count("tpu_custom_call") >= 2
    # and no [B,T,H,T] dense score tensor is ever materialized
    assert f"{T},4,{T}" not in txt.replace(" ", "")
