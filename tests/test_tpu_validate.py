"""Tests for tools/tpu_validate.py's per-group isolation (round 5).

A remote Mosaic compile can wedge the axon tunnel indefinitely — on the
first round-5 hardware window the inline script froze on its first kernel
and burned the battery step's whole 3600 s budget.  Isolated mode runs
each check group in its own subprocess so a wedge costs one group, and
re-probes the tunnel after a timeout so a dead tunnel aborts the rest.

Nothing here dials the tunnel: child subprocesses are faked by
monkeypatching the module's subprocess.Popen, and the probe is stubbed.
"""
import argparse
import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SCRIPT = os.path.join(REPO, "tools", "tpu_validate.py")


def _load(name="tpu_validate_under_test"):
    spec = importlib.util.spec_from_file_location(name, SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.RESULTS.clear()
    return mod


def _args(**over):
    base = dict(group_timeout=5.0, settle_s=0.0, probe_timeout=1.0,
                budget=300.0, out=None)
    base.update(over)
    return argparse.Namespace(**base)


class _FakeProc:
    """Stands in for a --only child: either returns canned JSON lines or
    'wedges' (communicate raises TimeoutExpired)."""

    def __init__(self, lines=None, wedge=False, returncode=0):
        self._lines = lines or []
        self._wedge = wedge
        self.returncode = returncode
        self.pid = os.getpid()          # killpg target; see fake killpg

    def communicate(self, timeout=None):
        if self._wedge:
            raise subprocess.TimeoutExpired(cmd="child", timeout=timeout)
        return "\n".join(json.dumps(l) for l in self._lines) + "\n", ""

    def kill(self):
        pass

    def wait(self):
        pass


def _install_children(monkeypatch, mod, procs):
    it = iter(procs)
    monkeypatch.setattr(mod.subprocess, "Popen",
                        lambda *a, **k: next(it))
    monkeypatch.setattr(mod.os, "killpg", lambda *a: None)


def test_isolated_merges_group_results(monkeypatch):
    mod = _load()
    dev = {"check": "device", "ok": True, "kind": "TPU v5 lite",
           "platform": "axon"}
    _install_children(monkeypatch, mod, [
        _FakeProc([dev, {"check": "a", "ok": True}]),
        _FakeProc([dev, {"check": "b", "ok": True}]),
    ])
    device = mod._run_isolated(_args(), ["fwd_1k", "fwd_768"])
    assert device == "TPU v5 lite"
    checks = [r["check"] for r in mod.RESULTS]
    assert checks == ["device", "a", "b"]       # device line echoed once
    assert all(r["ok"] for r in mod.RESULTS)


def test_isolated_wedged_group_costs_one_group(monkeypatch):
    """First group wedges; probe says the tunnel survived; the second
    group still runs and its results land."""
    mod = _load()
    dev = {"check": "device", "ok": True, "kind": "TPU v5 lite"}
    _install_children(monkeypatch, mod, [
        _FakeProc(wedge=True),
        _FakeProc([dev, {"check": "later", "ok": True}]),
    ])
    monkeypatch.setattr(mod, "_probe_alive", lambda t: True)
    mod._run_isolated(_args(), ["fwd_1k", "ring"])
    by_check = {r["check"]: r for r in mod.RESULTS}
    assert by_check["group_fwd_1k"]["ok"] is False
    assert by_check["group_fwd_1k"]["error"] == "timeout"
    assert by_check["later"]["ok"] is True


def test_isolated_dead_tunnel_skips_remaining_groups(monkeypatch):
    mod = _load()
    _install_children(monkeypatch, mod, [_FakeProc(wedge=True)])
    monkeypatch.setattr(mod, "_probe_alive", lambda t: False)
    mod._run_isolated(_args(), ["fwd_1k", "bwd_512", "ring"])
    by_check = {r["check"]: r for r in mod.RESULTS}
    assert by_check["group_fwd_1k"]["error"] == "timeout"
    assert "skipped" in by_check["group_bwd_512"]["error"]
    assert "skipped" in by_check["group_ring"]["error"]
    assert not any(r["ok"] for r in mod.RESULTS)


def test_isolated_child_crash_is_reported(monkeypatch):
    mod = _load()
    _install_children(monkeypatch, mod, [_FakeProc([], returncode=139)])
    mod._run_isolated(_args(), ["timing"])
    (rec,) = mod.RESULTS
    assert rec["check"] == "group_timing"
    assert rec["ok"] is False and "exit 139" in rec["error"]


def test_isolated_writes_out_incrementally(monkeypatch, tmp_path):
    """--out must be rewritten after every group so an outer kill (the
    battery's step timeout) keeps completed groups' results."""
    mod = _load()
    dev = {"check": "device", "ok": True, "kind": "TPU v5 lite"}
    out = str(tmp_path / "val.json")
    seen = []

    class Recorder(_FakeProc):
        def communicate(self, timeout=None):
            if os.path.exists(out):
                seen.append(json.load(open(out))["n_checks"])
            return super().communicate(timeout)

    it = iter([Recorder([dev, {"check": "a", "ok": True}]),
               Recorder([dev, {"check": "b", "ok": True}])])
    monkeypatch.setattr(mod.subprocess, "Popen", lambda *a, **k: next(it))
    mod._run_isolated(_args(out=out), ["fwd_1k", "fwd_768"])
    doc = json.load(open(out))
    assert doc["n_checks"] == 3 and doc["summary"] == "PASS"
    assert seen == [2]          # group 2 saw group 1's banked results


def test_isolated_budget_exhaustion_skips_rest(monkeypatch):
    mod = _load()
    _install_children(monkeypatch, mod, [])   # nothing may spawn
    mod._run_isolated(_args(budget=0.0), ["fwd_1k", "ring"])
    assert [r["check"] for r in mod.RESULTS] == ["group_fwd_1k",
                                                 "group_ring"]
    assert all("budget exhausted" in r["error"] for r in mod.RESULTS)


def test_accelerator_vanishing_mid_run_keeps_results(monkeypatch, tmp_path):
    """rc 2 from a LATER child (tunnel daemon restarted, CPU only) must
    not discard the groups already banked."""
    mod = _load()
    dev = {"check": "device", "ok": True, "kind": "TPU v5 lite"}
    out = str(tmp_path / "val.json")
    _install_children(monkeypatch, mod, [
        _FakeProc([dev, {"check": "early", "ok": True}]),
        _FakeProc([], returncode=2),
    ])
    mod._run_isolated(_args(out=out), ["fwd_1k", "ring"])
    doc = json.load(open(out))
    by_check = {r["check"]: r for r in doc["results"]}
    assert by_check["early"]["ok"] is True
    assert "vanished" in by_check["group_ring"]["error"]


def test_first_child_rc2_still_refuses(monkeypatch):
    mod = _load()
    _install_children(monkeypatch, mod, [_FakeProc([], returncode=2)])
    with pytest.raises(SystemExit) as e:
        mod._run_isolated(_args(), ["fwd_1k"])
    assert e.value.code == 2


def test_cpu_pin_refuses_without_spawning():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, SCRIPT], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 2
    assert "no accelerator" in p.stderr
    p = subprocess.run([sys.executable, SCRIPT, "--inline"], env=env,
                       cwd=REPO, capture_output=True, text=True, timeout=120)
    assert p.returncode == 2


def test_group_list_covers_all_checks():
    """Every check the old inline main ran has a group; the isolated
    default runs them all."""
    mod = _load()
    assert set(mod.GROUPS) == {"fwd_1k", "fwd_768", "bwd_512", "bwd_384",
                               "timing", "ring"}
