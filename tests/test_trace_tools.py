"""Tests for tools/trace_analyze.py (compute/comm/exposed-comm split) and
tools/perf_fill.py (PERFORMANCE.md auto-fill) — the post-processing stages
of the hw-watch battery.  The trace fixture is hand-written Chrome-trace
JSON: deterministic intervals whose overlap arithmetic is checkable by
hand, no profiler dependency."""
import gzip
import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _trace_doc():
    """Device track: compute [0,100)+[150,250)ms, comm [80,130)+[200,220)ms.
    comm total 70ms; exposed = [100,130) = 30ms; busy = [0,130)+[150,250);
    wall 250ms; idle = [130,150) = 20ms.  (Trace units are microseconds.)"""
    ms = 1000.0
    ev = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "python host"}},
        # device events
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1",
         "ts": 0, "dur": 100 * ms},
        {"ph": "X", "pid": 1, "tid": 2, "name": "collective-permute.3",
         "ts": 80 * ms, "dur": 50 * ms},
        {"ph": "X", "pid": 1, "tid": 1, "name": "dot.7",
         "ts": 150 * ms, "dur": 100 * ms},
        {"ph": "X", "pid": 1, "tid": 2, "name": "all-reduce.9",
         "ts": 200 * ms, "dur": 20 * ms},
        # host noise that must be ignored (device pids exist)
        {"ph": "X", "pid": 2, "tid": 1, "name": "python busywork",
         "ts": 0, "dur": 500 * ms},
    ]
    return {"traceEvents": ev}


def test_trace_analyze_overlap_arithmetic(tmp_path):
    ta = _load("trace_analyze")
    doc = ta.analyze(_trace_doc()["traceEvents"])
    assert doc["ok"] is True
    assert doc["n_events"] == 4                 # host track excluded
    assert abs(doc["wall_ms"] - 250.0) < 1e-6
    assert abs(doc["compute_ms"] - 200.0) < 1e-6
    assert abs(doc["comm_ms"] - 70.0) < 1e-6
    assert abs(doc["comm_exposed_ms"] - 30.0) < 1e-6
    assert abs(doc["overlap_fraction"] - (1 - 30.0 / 70.0)) < 1e-3
    assert abs(doc["idle_ms"] - 20.0) < 1e-6


def test_trace_analyze_cli_on_gzipped_dir(tmp_path):
    run = tmp_path / "plugins" / "profile" / "run1"
    run.mkdir(parents=True)
    with gzip.open(run / "host.trace.json.gz", "wt") as f:
        json.dump(_trace_doc(), f)
    out = tmp_path / "split.json"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_analyze.py"),
         str(tmp_path), "--out", str(out)],
        capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, p.stderr
    doc = json.load(open(out))
    assert doc["ok"] and doc["comm_exposed_ms"] == 30.0


def test_trace_analyze_fallback_busiest_track():
    ta = _load("trace_analyze")
    # no process_name metadata at all -> the busiest pid wins (here the
    # 500 ms host-noise track, proving the fallback keys on duration)
    ev = [e for e in _trace_doc()["traceEvents"] if e["ph"] == "X"]
    doc = ta.analyze(ev)
    assert doc["ok"] and doc["n_events"] == 1
    # with the noise gone, the remaining single-pid trace analyzes fully
    doc = ta.analyze([e for e in ev if e["pid"] == 1])
    assert doc["ok"] and doc["n_events"] == 4


def test_comm_re_classification():
    """Pin the comm-vs-compute classifier: every modern collective spelling
    (ragged-all-to-all, fusion-wrapped async -start/-done forms, bare
    send/recv) is comm; fusions, copies, and convolutions are compute —
    copy-start/copy-done especially must NOT ride the '-start' suffix into
    the comm bucket."""
    ta = _load("trace_analyze")
    comm = [
        "ragged-all-to-all.1", "all-reduce-start.2", "all-reduce-done.2",
        "loop_fusion.collective-permute-start.5", "AllToAll.9",
        "all_gather.4", "reduce_scatter.1", "collective-broadcast.2",
        "send.3", "recv-done.4", "ppermute",
    ]
    compute = [
        "fusion.42", "copy-start.1", "copy-done.1",
        "dynamic-update-slice.7", "convolution.2", "dot.11",
    ]
    for name in comm:
        assert ta.COMM_RE.search(name), f"should be comm: {name}"
    for name in compute:
        assert not ta.COMM_RE.search(name), f"should be compute: {name}"


def test_obs_trace_fixture_arithmetic():
    """The committed obs-smoke fixture (Makefile `obs-smoke` runs the CLI
    on the same file): compute [0,100)+[150,250), comm
    [80,140)+[200,220), exposed [100,140), idle [140,150)."""
    ta = _load("trace_analyze")
    doc = json.load(open(
        os.path.join(REPO, "tests", "fixtures", "obs_trace.trace.json")))
    out = ta.analyze(doc["traceEvents"])
    assert out["ok"] and out["n_events"] == 8
    assert abs(out["wall_ms"] - 250.0) < 1e-6
    assert abs(out["compute_ms"] - 200.0) < 1e-6
    assert abs(out["comm_ms"] - 80.0) < 1e-6
    assert abs(out["comm_exposed_ms"] - 40.0) < 1e-6
    assert abs(out["overlap_fraction"] - 0.5) < 1e-3
    assert abs(out["idle_ms"] - 10.0) < 1e-6


def test_canonical_op_strips_instance_suffixes():
    ta = _load("trace_analyze")
    assert ta.canonical_op("collective-permute-start.5") == \
        "collective-permute-start"
    assert ta.canonical_op("all-reduce.2.1") == "all-reduce"
    assert ta.canonical_op("fusion") == "fusion"
    assert ta.canonical_op("") == ""


def test_overlap_trace_fixture_per_op_attribution():
    """The committed overlapped-step fixture (`make overlap-smoke` runs the
    CLI on the same file): compute [0,140)+[150,200), comm
    [10,50)+[120,160)+[200,220).  Aggregate: comm 100ms, exposed
    [140,150)+[200,220) = 30ms, overlap 0.70, wall 220ms, idle 0.
    Per-op: the two permute-starts canonicalize to one row (80ms total,
    10ms exposed); the trailing permute-done is fully exposed (20ms) and
    must rank first."""
    ta = _load("trace_analyze")
    doc = json.load(open(
        os.path.join(REPO, "tests", "fixtures", "overlap_trace.trace.json")))
    out = ta.analyze(doc["traceEvents"])
    assert out["ok"] and out["n_events"] == 6       # host track excluded
    assert abs(out["wall_ms"] - 220.0) < 1e-6
    assert abs(out["compute_ms"] - 190.0) < 1e-6
    assert abs(out["comm_ms"] - 100.0) < 1e-6
    assert abs(out["comm_exposed_ms"] - 30.0) < 1e-6
    assert abs(out["overlap_fraction"] - 0.70) < 1e-3
    assert abs(out["idle_ms"] - 0.0) < 1e-6
    rows = out["top_exposed_comm_ops"]
    assert [r["name"] for r in rows] == [
        "collective-permute-done", "collective-permute-start"]
    assert rows[0]["count"] == 1
    assert abs(rows[0]["total_ms"] - 20.0) < 1e-6
    assert abs(rows[0]["exposed_ms"] - 20.0) < 1e-6
    assert rows[1]["count"] == 2
    assert abs(rows[1]["total_ms"] - 80.0) < 1e-6
    assert abs(rows[1]["exposed_ms"] - 10.0) < 1e-6


def test_top_exposed_comm_ops_on_obs_fixture():
    """Per-op attribution over the obs fixture, hand-checked: the ragged
    all-to-all owns 30 of the 40 exposed ms, the fusion-wrapped permute
    owns 20 (their [120,130) overlap is attributed to BOTH — per-op rows
    may double-count time that two comm ops expose simultaneously, so the
    rows bound the aggregate from above), the async all-reduce halves are
    fully hidden and tie-break by name."""
    ta = _load("trace_analyze")
    doc = json.load(open(
        os.path.join(REPO, "tests", "fixtures", "obs_trace.trace.json")))
    out = ta.analyze(doc["traceEvents"])
    rows = out["top_exposed_comm_ops"]
    assert [r["name"] for r in rows] == [
        "ragged-all-to-all", "loop_fusion.collective-permute-start",
        "all-reduce-done", "all-reduce-start"]
    assert [r["exposed_ms"] for r in rows] == [30.0, 20.0, 0.0, 0.0]
    assert sum(r["exposed_ms"] for r in rows) >= out["comm_exposed_ms"]


def test_perf_fill_renders_and_is_idempotent(tmp_path, monkeypatch):
    measured = tmp_path / "measured"
    measured.mkdir()
    (measured / "bench_rX.json").write_text(json.dumps({
        "ok": True, "value": 321.5, "unit": "img/s/chip", "mfu": 0.41,
        "vs_baseline": 1.19, "on_accelerator": True, "device": "TPU v5e"}))
    (measured / "trace_split_rX.json").write_text(json.dumps({
        "ok": True, "busy_ms": 1, "wall_ms": 2, "idle_ms": 1,
        "compute_ms": 0.8, "comm_ms": 0.4, "comm_exposed_ms": 0.1,
        "overlap_fraction": 0.75}))
    monkeypatch.setenv("BLUEFOG_MEASURED_DIR", str(measured))
    pf = _load("perf_fill")

    filled = pf.fill("rX", dry_run=True)
    assert "321.5 img/s/chip" in filled
    assert "41.0%" in filled                      # MFU formatted
    assert "overlap fraction 0.75" in filled
    assert filled.count(pf.BEGIN) == 1
    # the artifact above predates batch/steps_per_call: the config suffix
    # must be omitted entirely, not rendered as a literal "bNone·kNone"
    assert "bNone" not in filled and "kNone" not in filled
    # idempotent: writing again replaces the marked block, not appends
    open_orig = pf.PERF
    try:
        perf_copy = tmp_path / "PERFORMANCE.md"
        perf_copy.write_text(open(open_orig).read())
        pf.PERF = str(perf_copy)
        pf.fill("rX")
        once = perf_copy.read_text()
        pf.fill("rX")
        twice = perf_copy.read_text()
        assert once.count(pf.BEGIN) == 1
        assert twice.count(pf.BEGIN) == 1
        assert "321.5" in once
        # truncated-block recovery: BEGIN without END (kill mid-write)
        # must not duplicate the block on the next fill
        perf_copy.write_text(once[:once.index(pf.END)])
        pf.fill("rX")
        healed = perf_copy.read_text()
        assert healed.count(pf.BEGIN) == 1
        assert healed.count(pf.END) == 1
    finally:
        pf.PERF = open_orig


def test_perf_fill_renders_config_suffix_and_roofline(tmp_path, monkeypatch):
    """Artifacts WITH the r06 fields: the headline row carries the
    b<batch>·k<steps> config, and a banked roofline renders with its
    trusted/suspect verdicts."""
    measured = tmp_path / "measured"
    measured.mkdir()
    (measured / "bench_rY.json").write_text(json.dumps({
        "ok": True, "value": 1961.25, "unit": "img/s/chip", "mfu": 0.12,
        "vs_baseline": 7.28, "on_accelerator": True, "device": "TPU v5e",
        "batch_per_chip": 64, "steps_per_call": 5}))
    (measured / "roofline_rY.json").write_text(json.dumps({
        "ok": True, "device": "TPU v5 lite",
        "mxu": [
            {"probe": "mxu_bf16_8192", "tflops": 150.2,
             "flops_per_sec": 150.2e12, "trusted": True, "suspect": False},
            {"probe": "mxu_bf16_4096", "tflops": 641.0,
             "flops_per_sec": 641e12, "trusted": False, "suspect": True,
             "note": "rate tripwire"},
        ],
        "hbm": [{"probe": "hbm_rw_1024MiB", "gbps": 780.0,
                 "dispatch_corrected_gbps": 800.0,
                 "trusted": True, "suspect": False}]}))
    monkeypatch.setenv("BLUEFOG_MEASURED_DIR", str(measured))
    pf = _load("perf_fill")
    filled = pf.fill("rY", dry_run=True)
    assert "b64·k5" in filled
    assert "150.2 TFLOP/s — trusted" in filled
    assert "**SUSPECT, rejected**" in filled
    assert "780.0 GB/s (dispatch-corrected 800.0)" in filled
