"""Request-scoped tracing, the time-series store, and SLO tripwires.

Four layers under test:

* ``bluefog_tpu/utils/tracing.py`` — the span store: id minting, the
  bounded ring, JSONL bundles, env arming, and the hot-path cost pin
  (the flight-recorder discipline: one bool check disarmed);
* ``bluefog_tpu/utils/timeseries.py`` — bounded per-metric history:
  ring windows, exact percentiles, rates, the registry hook that feeds
  rings from live metrics, and point-clearing on ``reset_metrics``;
* ``bluefog_tpu/diagnostics.SLOEngine`` — multi-window burn rates over
  the store plus the four anomaly tripwires;
* ``tools/trace_report.py`` / ``tools/metrics_report.py`` /
  ``tools/postmortem.py`` — the operator-facing consumers, pinned
  against committed fixtures.

Plus the PR's acceptance drill: the 8-rank train→serve estate with
tracing armed — per-rank bundles merge into a critical-path table whose
per-request total equals the scheduler's own measured E2E latency, with
donation intact and the retrace sentinel at 0 (observability stays free).
"""
import importlib.util
import json
import os
import time

import numpy as np
import pytest

from bluefog_tpu.diagnostics import DEFAULT_SLO_WINDOWS, SLOEngine
from bluefog_tpu.parallel import compose
from bluefog_tpu.serve import Scheduler, ServeConfig, ServeEngine
from bluefog_tpu.utils import flight as bfflight
from bluefog_tpu.utils import metrics as bfm
from bluefog_tpu.utils import timeseries as bfts
from bluefog_tpu.utils import tracing as bftrace

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


@pytest.fixture(autouse=True)
def _clean():
    bfm.reset_metrics()
    bfts.reset()
    bftrace.reset()
    bfflight.reset()
    yield
    bftrace.reset()
    bfts.reset()
    bfm.reset_metrics()
    bfflight.reset()


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name.replace("/", "_") + "_mod", os.path.join(REPO, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# tracing.py: the span store
# ---------------------------------------------------------------------------

def test_disarmed_recorder_is_inert():
    assert not bftrace.enabled()
    assert bftrace.add_span("t", "x", 0.0, 1.0) == 0
    assert bftrace.mark("t", "m") == 0
    with bftrace.span("t", "blk") as s:
        pass
    assert s.id == 0
    assert bftrace.spans() == [] and bftrace.dropped() == 0


def test_arm_record_flush_roundtrip(tmp_path):
    bftrace.configure(str(tmp_path))
    assert bftrace.enabled()
    t1, t2 = bftrace.new_trace("req"), bftrace.new_trace("req")
    assert t1 != t2 and t1.startswith("req-r")
    sid = bftrace.add_span(t1, "queue", 1.0, 2.0, cat="serve", replica=3)
    assert sid > 0
    bftrace.add_span(t1, "decode", 2.0, 2.5, cat="serve",
                     parent=sid, tokens=2)
    with bftrace.span(t2, "prefill", cat="serve") as s:
        s.attrs["hit"] = True
    assert s.id > 0
    path = bftrace.flush()
    assert path == bftrace.bundle_path()
    lines = [json.loads(l) for l in open(path) if l.strip()]
    meta, spans = lines[0], lines[1:]
    assert meta["kind"] == "meta" and meta["schema"] == bftrace.SCHEMA
    assert {"rank", "mono", "wall", "n_spans", "dropped"} <= set(meta)
    assert meta["n_spans"] == len(spans) == 3
    by_name = {s["name"]: s for s in spans}
    assert by_name["queue"]["replica"] == 3
    assert by_name["decode"]["parent"] == sid
    assert by_name["prefill"]["hit"] is True
    # atomic write: no tmp file left behind
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_ring_bound_counts_drops(tmp_path):
    bftrace.configure(str(tmp_path), capacity=4)
    t = bftrace.new_trace()
    for i in range(10):
        bftrace.add_span(t, f"s{i}", float(i), float(i) + 0.5)
    assert len(bftrace.spans()) == 4
    assert bftrace.dropped() == 6
    assert [s["name"] for s in bftrace.spans()] == ["s6", "s7", "s8", "s9"]


def test_env_arming(tmp_path, monkeypatch):
    monkeypatch.delenv(bftrace.ENV_TRACE, raising=False)
    assert not bftrace.maybe_enable_from_env()
    monkeypatch.setenv(bftrace.ENV_TRACE, str(tmp_path))
    assert bftrace.maybe_enable_from_env() and bftrace.enabled()
    assert bftrace.bundle_path().startswith(str(tmp_path))


def test_hot_path_cost_pin(tmp_path):
    """The flight-recorder cost discipline: disarmed add_span is one bool
    check (sub-microsecond); armed it is one dict build + deque append.
    Bounds are ~10x slack over measured so CI noise cannot flake them."""
    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        bftrace.add_span("t", "x", 0.0, 1.0)
    disarmed = (time.perf_counter() - t0) / n
    bftrace.configure(str(tmp_path))
    tr = bftrace.new_trace()
    t0 = time.perf_counter()
    for i in range(n):
        bftrace.add_span(tr, "x", 0.0, 1.0, cat="serve", call=i)
    armed = (time.perf_counter() - t0) / n
    assert disarmed < 5e-6, f"disarmed add_span {disarmed * 1e6:.2f}us/call"
    assert armed < 50e-6, f"armed add_span {armed * 1e6:.2f}us/call"


# ---------------------------------------------------------------------------
# timeseries.py: the bounded history store
# ---------------------------------------------------------------------------

def test_ring_window_and_stats():
    bfts.arm("m")
    for i in range(10):
        bfts.append("m", float(i), ts=float(i))
    assert bfts.latest("m") == 9.0
    assert bfts.mean("m") == pytest.approx(4.5)
    # window: ts >= now - window_s (inclusive cut)
    assert [v for _, v in bfts.history("m", window_s=3.0, now=9.0)] == \
        [6.0, 7.0, 8.0, 9.0]
    assert bfts.percentile("m", 0) == 0.0
    assert bfts.percentile("m", 100) == 9.0
    assert bfts.percentile("m", 50, window_s=3.0, now=9.0) == 8.0
    assert bfts.rate("m") == pytest.approx(1.0)     # +1 per 1s tick
    assert bfts.over_fraction("m", 6.5) == pytest.approx(0.3)
    assert bfts.percentile("empty", 50) is None
    assert bfts.over_fraction("empty", 1.0) is None


def test_ring_capacity_bound():
    r = bfts.arm("m", capacity=8)
    for i in range(100):
        bfts.append("m", float(i), ts=float(i))
    assert len(r.values()) == 8
    assert r.values()[0] == 92.0


def test_registry_metrics_feed_armed_rings():
    bfts.arm("h")
    h = bfm.histogram("h", "test", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 2.0):
        h.observe(v)
    assert bfts.history("h") is not None
    assert [v for _, v in bfts.history("h")] == [0.05, 0.5, 2.0]  # raw values
    bfts.arm("g")
    bfm.gauge("g", "test").set(7.0)
    assert bfts.latest("g") == 7.0
    bfts.arm("c")
    c = bfm.counter("c", "test")
    c.inc(2.0)
    c.inc(3.0)
    assert [v for _, v in bfts.history("c")] == [2.0, 5.0]  # cumulative
    # an unarmed metric stays out of the store
    bfm.gauge("unarmed", "test").set(1.0)
    assert not bfts.armed("unarmed")


def test_reset_metrics_clears_points_keeps_arming():
    bfts.arm("g")
    bfm.gauge("g", "test").set(1.0)
    assert bfts.latest("g") == 1.0
    bfm.reset_metrics()
    assert bfts.armed("g")                 # arming survives
    assert bfts.latest("g") is None        # stale points do not
    bfm.gauge("g", "test").set(2.0)        # re-created metric re-attaches
    assert bfts.latest("g") == 2.0


# ---------------------------------------------------------------------------
# SLOEngine: burn rates + tripwires
# ---------------------------------------------------------------------------

_LAT = "bluefog_serve_token_latency_seconds"


class _StubSched:
    def __init__(self, pending=0, in_flight=0):
        self.pending, self.in_flight = pending, in_flight
        self.completed, self.failed = [], []


def test_burn_rate_math():
    eng = SLOEngine(p99_ms=100.0)
    assert dict(DEFAULT_SLO_WINDOWS) == {"5m": 300.0, "1h": 3600.0}
    now = 1000.0
    # 20 latency points, 2 over the 100 ms target -> bad fraction 0.1,
    # burn = 0.1 / 0.01 budget = 10.0
    for i in range(20):
        bfts.append(_LAT, 0.5 if i < 2 else 0.01, ts=now - 19 + i)
    burn = eng.burn_rates(now=now)
    assert burn[("5m", "p99")] == pytest.approx(10.0)
    assert burn[("1h", "p99")] == pytest.approx(10.0)
    assert burn[("5m", "ttft")] is None            # no TTFT events yet
    assert eng.breached()[("5m", "p99")] == pytest.approx(10.0)
    g = bfm.gauge("bluefog_slo_burn_rate")
    assert g.value(window="5m", slo="p99") == pytest.approx(10.0)


def test_availability_burn_from_scheduler_counts():
    eng = SLOEngine(availability=0.9)              # 10% error budget
    sched = _StubSched()
    sched.completed = [1, 2, 3]
    sched.failed = [4]                             # 25% bad / 0.1 budget
    out = eng.observe(sched, now=10.0)
    assert out["burn_rates"][("5m", "availability")] == pytest.approx(2.5)


def test_slo_fast_burn_tripwire_and_cooldown():
    eng = SLOEngine(p99_ms=100.0, burn_alert_threshold=10.0,
                    tripwire_cooldown=5)
    now = 100.0
    for i in range(10):                            # 50% bad -> burn 50
        bfts.append(_LAT, 0.5 if i % 2 else 0.01, ts=now - 9 + i)
    out = eng.observe(now=now)
    assert [f["kind"] for f in out["tripwires"]] == ["slo_fast_burn"]
    assert bfm.counter("bluefog_tripwire_total").value(
        kind="slo_fast_burn") == 1
    ev = [e for e in bfflight.events() if e["kind"] == "tripwire"]
    assert ev and ev[-1]["name"] == "slo_fast_burn"
    assert ev[-1]["slo"] == "p99" and ev[-1]["burn"] > 10.0
    # cooldown: the next observes stay quiet until it expires
    for _ in range(3):
        assert eng.observe(now=now)["tripwires"] == []
    for _ in range(2):
        eng.observe(now=now)
    assert bfm.counter("bluefog_tripwire_total").value(
        kind="slo_fast_burn") == 2


def test_step_time_regression_tripwire():
    eng = SLOEngine(step_baseline_n=5, step_time_factor=2.0)
    # banked baseline: first 5 observations ~0.1 s; recent mean 0.5 s
    for i in range(5):
        bfts.append("bluefog_step_time_s", 0.1, ts=float(i))
    for i in range(5):
        bfts.append("bluefog_step_time_s", 0.5, ts=5.0 + i)
    out = eng.observe(now=10.0)
    fired = [f for f in out["tripwires"]
             if f["kind"] == "step_time_regression"]
    assert fired and fired[0]["baseline_s"] == pytest.approx(0.1)
    assert fired[0]["factor"] == pytest.approx(5.0)


def test_step_regression_quiet_while_banking():
    eng = SLOEngine(step_baseline_n=5)
    for i in range(6):                  # < 2n points: baseline still banking
        bfts.append("bluefog_step_time_s", 0.1 * (i + 1), ts=float(i))
    assert eng.observe(now=6.0)["tripwires"] == []


def test_consensus_stall_tripwire():
    eng = SLOEngine(consensus_factor=2.0)
    for i, v in enumerate((1.0, 0.1, 1.5)):       # contracted then re-expanded
        bfts.append("bluefog_consensus_distance_max", v, ts=float(i))
    out = eng.observe(now=3.0)
    fired = [f for f in out["tripwires"] if f["kind"] == "consensus_stall"]
    assert fired and fired[0]["latest_distance"] == pytest.approx(1.5)
    # a contracting trace never fires
    bfm.reset_metrics()
    eng2 = SLOEngine()
    for i, v in enumerate((1.0, 0.5, 0.1)):
        bfts.append("bluefog_consensus_distance_max", v, ts=float(i))
    assert eng2.observe(now=3.0)["tripwires"] == []


def test_queue_growth_idle_tripwire():
    eng = SLOEngine(idle_steps=3)
    sched = _StubSched(pending=4, in_flight=0)
    assert eng.observe(sched)["tripwires"] == []
    assert eng.observe(sched)["tripwires"] == []
    out = eng.observe(sched)
    assert [f["kind"] for f in out["tripwires"]] == ["queue_growth_idle"]
    assert out["tripwires"][0]["pending"] == 4
    # any progress resets the streak
    eng2 = SLOEngine(idle_steps=2)
    busy = _StubSched(pending=4, in_flight=1)
    idle = _StubSched(pending=4, in_flight=0)
    eng2.observe(idle)
    eng2.observe(busy)
    assert eng2.observe(idle)["tripwires"] == []


def test_slo_env_defaults(monkeypatch):
    monkeypatch.setenv("BLUEFOG_SLO_P99_MS", "123")
    monkeypatch.setenv("BLUEFOG_SLO_TTFT_MS", "456")
    monkeypatch.setenv("BLUEFOG_SLO_AVAILABILITY", "0.95")
    eng = SLOEngine()
    assert eng.p99_s == pytest.approx(0.123)
    assert eng.ttft_s == pytest.approx(0.456)
    assert eng.availability == pytest.approx(0.95)
    with pytest.raises(ValueError):
        SLOEngine(availability=1.5)


# ---------------------------------------------------------------------------
# tools/trace_report.py vs the committed fixtures
# ---------------------------------------------------------------------------

def _fixture_bundles():
    return [os.path.join(FIXTURES, f"trace_rank{r}.trace.jsonl")
            for r in (0, 1)]


def test_trace_report_fixture_schema_and_breakdown():
    tr = _load_tool("tools/trace_report")
    doc, bundles = tr.report_from_files(_fixture_bundles())
    assert doc["ok"] and doc["schema"] == "bluefog-trace-report-1"
    assert doc["n_ranks"] == 2 and doc["ranks"] == [0, 1]
    assert doc["n_spans"] == 10 and doc["dropped"] == 2
    req = doc["requests"]["req-r0-1"]
    assert req["total_s"] == pytest.approx(0.08)
    assert req["queue_s"] == pytest.approx(0.01)
    assert req["prefill_s"] == pytest.approx(0.02)
    assert req["decode_s"] == pytest.approx(0.04)
    assert req["gap_s"] == pytest.approx(0.01)
    # the construction invariant: parts sum exactly to the E2E total
    assert req["queue_s"] + req["prefill_s"] + req["decode_s"] \
        + req["gap_s"] == pytest.approx(req["total_s"])
    assert req["n_decode_calls"] == 2 and req["prefix_hit"] is False
    assert req["tokens"] == 4 and req["replica"] == 0
    assert doc["critical_path"][0][0] == "req-r0-1"
    assert doc["train"] == {"steps": 2, "step_mean_s": 0.2, "probes": 1}
    # chrome trace: per-rank pids, metadata lanes, non-negative rel times
    ch = tr.chrome_trace(bundles)
    xs = [e for e in ch["traceEvents"] if e["ph"] == "X"]
    ms = [e for e in ch["traceEvents"] if e["ph"] == "M"]
    assert {e["pid"] for e in xs} == {0, 1}
    assert any(e["name"] == "process_name" for e in ms)
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in xs)
    # rank1 spans sit 0.5 s of wall clock after rank0's anchor
    r1 = min(e["ts"] for e in xs if e["pid"] == 1)
    assert r1 == pytest.approx(0.5e6, abs=1e3)


def test_trace_report_torn_line_and_bad_schema(tmp_path):
    tr = _load_tool("tools/trace_report")
    good = os.path.join(FIXTURES, "trace_rank0.trace.jsonl")
    torn = tmp_path / "torn.trace.jsonl"
    torn.write_text(open(good).read() + '{"kind": "span", "tru')
    doc, _ = tr.report_from_files([str(torn)])
    assert doc["ok"] and any("torn" in n for n in doc["notes"])
    bad = tmp_path / "bad.trace.jsonl"
    bad.write_text('{"kind": "meta", "schema": "nope", "mono": 0, "wall": 0}\n')
    with pytest.raises(ValueError):
        tr.load_bundle(str(bad))


# ---------------------------------------------------------------------------
# tools/metrics_report.py: histogram percentiles (vs committed fixtures)
# ---------------------------------------------------------------------------

def test_bucket_percentile_math():
    mr = _load_tool("tools/metrics_report")
    # 10 events: 5 in (0, 0.1], 4 in (0.1, 1.0], 1 overflow
    buckets = [[0.1, 5], [1.0, 4], ["+Inf", 1]]
    assert mr._bucket_percentile(buckets, 50) == pytest.approx(0.1)
    # p90 = 9th event: 4/4 through the (0.1, 1.0] bucket -> its far edge
    assert mr._bucket_percentile(buckets, 90) == pytest.approx(1.0)
    assert mr._bucket_percentile(buckets, 99) == pytest.approx(1.0)  # +Inf clamp
    assert mr._bucket_percentile([[0.1, 0], ["+Inf", 0]], 50) is None
    ps = mr._bucket_percentiles(buckets)
    assert set(ps) == {"p50", "p90", "p99"}


def test_metrics_report_percentiles_on_fixture():
    mr = _load_tool("tools/metrics_report")
    doc = mr.report_from_files(
        [os.path.join(FIXTURES, f"metrics_host{h}.metrics.jsonl")
         for h in (0, 1)])
    assert doc["ok"] and doc["n_hosts"] == 2
    hists = {n: m for n, m in doc["metrics"].items()
             if m.get("type") == "histogram" and m.get("buckets")}
    assert hists, "fixtures must carry at least one histogram"
    for name, h in hists.items():
        ps = h["percentiles"]
        assert set(ps) == {"p50", "p90", "p99"}, name
        vals = [ps["p50"], ps["p90"], ps["p99"]]
        assert all(v is not None for v in vals), name
        assert vals == sorted(vals), f"{name}: percentiles not monotone"
    st = doc["summary"]["step_time_s"]
    assert {"p50", "p90", "p99"} <= set(st)
    assert st["p50"] <= st["p99"]


# ---------------------------------------------------------------------------
# tools/postmortem.py: a dead replica's lost requests are NAMED
# ---------------------------------------------------------------------------

def test_postmortem_names_lost_requests():
    pm = _load_tool("tools/postmortem")
    bundles = {
        0: {
            "serve": {
                "dead_replicas": [1],
                "failed": [],
                "in_flight_traces": {
                    "0": [{"id": 3, "trace": "req-r0-4", "age_s": 0.25,
                           "queue_s": 0.01}],
                },
                "queued": [
                    {"id": 5, "trace": "req-r0-6", "age_s": 1.5},
                    {"id": 6, "trace": "req-r0-7", "age_s": 1.2},
                ],
            },
            "events": [
                {"kind": "serve", "name": "replica_killed", "replica": 1,
                 "requeued_requests": [5, 6]},
            ],
        },
    }
    notes = []
    out = pm._serve_block(bundles, notes)
    assert out["dead_replicas"] == [1]
    rows = out["lost_requests"]["1"]
    assert [r["id"] for r in rows] == [5, 6]
    assert rows[0]["trace"] == "req-r0-6"
    named = [n for n in notes if "went down holding" in n]
    assert named and "req 5 (trace req-r0-6, age 1.500s)" in named[0]
    assert "req 6 (trace req-r0-7" in named[0]


# ---------------------------------------------------------------------------
# The acceptance drill: traced 8-rank estate, breakdown == measured E2E
# ---------------------------------------------------------------------------

_CFG = dict(vocab=32, d_model=32, heads=4, layers=4, seq_len=32)


def _serve_estate(cpu_devices, seed=7):
    """2 training replicas (pp=2) on devices 0-3, 2 serving replicas
    (pp=2) on devices 4-7 — the test_serve estate shape."""
    import optax
    import bluefog_tpu.optimizers as bfopt

    cfg = compose.LMConfig(**_CFG)
    train_m = compose.compose_parallelism(2, 2, 1, 1,
                                          devices=cpu_devices[:4])
    serve_m = compose.compose_parallelism(2, 2, 1, 1,
                                          devices=cpu_devices[4:])
    grad_fn = compose.make_lm_grad_fn(cfg, train_m)
    step, strategy = compose.make_train_step(
        train_m, grad_fn, optax.sgd(0.05))
    train_params = compose.init_lm_params(cfg, train_m, seed=1)
    state = bfopt.init_distributed(strategy, train_params)
    toks = compose.make_lm_batch(cfg, train_m)
    train_params = compose.device_put(train_m, train_params)
    scfg = ServeConfig(batch_buckets=(1, 2, 4), prefill_buckets=(4, 8),
                       slots=4, max_len=32)
    eng = ServeEngine(serve_m, cfg,
                      compose.init_lm_params(cfg, serve_m, seed=seed), scfg)
    eng.warmup()
    return cfg, (step, state, train_params, toks), eng


# The three estate drills below compile the full train→serve estate each
# (~10 s apiece) — tier-1 keeps only the host-side battery above; the
# drills gate `make obs-trace-smoke`, which runs this file unfiltered.
@pytest.mark.slow
def test_traced_estate_breakdown_matches_measured_e2e(cpu_devices, tmp_path):
    """Tracing armed over the full train→serve estate: the merged report's
    per-request total IS the scheduler's measured E2E latency (same clock,
    same stamps — equal to the ms), parts sum to the total, train spans
    ride alongside, and the whole thing costs nothing the invariants can
    see: donation intact, zero retraces."""
    import jax

    cfg, (step, state, train_params, toks), eng = _serve_estate(cpu_devices)
    bftrace.configure(str(tmp_path))
    sched = Scheduler(eng)
    cache_probe = eng.cache["k"]

    rng = np.random.default_rng(0)
    reqs = [sched.submit(rng.integers(0, cfg.vocab,
                                      int(rng.integers(2, 9))).tolist(),
                         max_new_tokens=int(rng.integers(2, 6)))
            for _ in range(12)]
    train_done, guard = 0, 0
    while not sched.done:
        guard += 1
        assert guard < 500, "scheduler failed to drain"
        sched.step()
        if train_done < 3:
            train_params, state, loss = step(train_params, state, toks)
            jax.block_until_ready(loss)
            train_done += 1

    assert len(sched.completed) == 12
    bundle = bftrace.flush()
    tr = _load_tool("tools/trace_report")
    doc, _ = tr.report_from_files([bundle])
    assert doc["ok"] and doc["dropped"] == 0

    # every retired request has a row whose total equals the measured E2E
    for req in reqs:
        row = doc["requests"][req.trace_id]
        e2e = req.finished_at - req.submitted_at
        assert row["total_s"] == pytest.approx(e2e, abs=1e-3)
        assert row["queue_s"] + row["prefill_s"] + row["decode_s"] \
            + row["gap_s"] == pytest.approx(row["total_s"], abs=1e-6)
        assert row["n_decode_calls"] >= 1
        assert row["tokens"] == req.max_new_tokens
    # the critical path is the slowest request, and is one of ours
    slowest = max(reqs, key=lambda r: r.finished_at - r.submitted_at)
    assert doc["critical_path"][0][0] == slowest.trace_id
    # train + engine spans rode along in the same bundle
    assert doc["train"]["steps"] == 3
    cats = {s.get("cat") for s in bftrace.spans()}
    assert {"serve", "engine", "train"} <= cats

    # observability stayed free: donation intact, nothing retraced
    assert cache_probe.is_deleted()
    assert bfm.counter("bluefog_retrace_after_warmup_total").total() == 0
    sched.close()


@pytest.mark.slow
def test_flash_crowd_burn_crosses_threshold_and_trips(cpu_devices):
    """The acceptance's SLO leg: a flash-crowd burst against an
    impossible latency target drives the 5m p99 burn-rate gauge past the
    fast-burn threshold and records a tripwire flight event."""
    cfg, _, eng = _serve_estate(cpu_devices)
    sched = Scheduler(eng)
    slo = SLOEngine(p99_ms=0.001, burn_alert_threshold=10.0)
    sched.attach_slo(slo)

    rng = np.random.default_rng(1)
    for _ in range(16):                           # the crowd arrives at once
        sched.submit(rng.integers(0, cfg.vocab,
                                  int(rng.integers(2, 9))).tolist(),
                     max_new_tokens=3)
    guard = 0
    while not sched.done:
        guard += 1
        assert guard < 500
        sched.step()

    assert len(sched.completed) == 16
    burn = slo.last_burn[("5m", "p99")]
    assert burn is not None and burn > 10.0       # budget torched
    assert bfm.gauge("bluefog_slo_burn_rate").value(
        window="5m", slo="p99") == pytest.approx(burn)
    assert any(f["kind"] == "slo_fast_burn" for f in slo.fired)
    ev = [e for e in bfflight.events() if e["kind"] == "tripwire"]
    assert ev and ev[0]["name"] == "slo_fast_burn"
    assert bfm.counter("bluefog_tripwire_total").value(
        kind="slo_fast_burn") >= 1
    sched.close()


@pytest.mark.slow
def test_tracing_and_timeseries_overhead_invariants(cpu_devices, tmp_path):
    """Satellite pin: with tracing AND per-metric history both armed, a
    warmed serve loop still donates its carry and compiles nothing new —
    the whole observability stack rides outside the jit boundary."""
    cfg, _, eng = _serve_estate(cpu_devices)
    bftrace.configure(str(tmp_path))
    slo = SLOEngine()                  # arms the latency/TTFT/step rings
    sched = Scheduler(eng)
    sched.attach_slo(slo)
    cache_probe = eng.cache["k"]
    rng = np.random.default_rng(2)
    for _ in range(8):
        sched.submit(rng.integers(0, cfg.vocab,
                                  int(rng.integers(2, 9))).tolist(),
                     max_new_tokens=4)
    sched.drain()
    assert len(sched.completed) == 8
    assert cache_probe.is_deleted()
    assert bfm.counter("bluefog_retrace_after_warmup_total").total() == 0
    assert bfts.history(_LAT), "armed latency ring must have filled"
    assert len(bftrace.spans()) > 0
    sched.close()


def test_trace_report_since_last_window(tmp_path):
    tr = _load_tool("tools/trace_report")
    # window_bounds: later bound wins; non-positive --last rejected
    assert tr.window_bounds(since=50.0, last=10.0, now=100.0) == 90.0
    assert tr.window_bounds(since=95.0, last=10.0, now=100.0) == 95.0
    assert tr.window_bounds() is None
    with pytest.raises(ValueError):
        tr.window_bounds(last=-1)

    # anchor with wall == mono so span endpoints read as wall times
    lines = [json.dumps({"kind": "meta", "schema": "bluefog-trace-1",
                         "rank": 0, "mono": 0.0, "wall": 0.0}),
             json.dumps({"kind": "span", "seq": 0, "trace": "t", "span": 1,
                         "name": "train_step", "cat": "train",
                         "t0": 1.0, "t1": 5.0, "step": 1}),
             json.dumps({"kind": "span", "seq": 1, "trace": "t", "span": 2,
                         "name": "train_step", "cat": "train",
                         "t0": 8.0, "t1": 12.0, "step": 2})]
    p = tmp_path / "w.trace.jsonl"
    p.write_text("\n".join(lines) + "\n")

    doc, _ = tr.report_from_files([str(p)])
    assert doc["n_spans"] == 2 and "window" not in doc

    # the span that *ended* before the cut is dropped (and noted)...
    doc, _ = tr.report_from_files([str(p)], since=6.0)
    assert doc["n_spans"] == 1 and doc["train"]["steps"] == 1
    assert doc["window"] == {"since_ts": 6.0}
    assert any("dropped 1 span" in n for n in doc["notes"])

    # ...but a span still *running into* the window is kept: t1 inside
    doc, _ = tr.report_from_files([str(p)], since=4.0)
    assert doc["n_spans"] == 2
