"""Ulysses (all-to-all head-scatter) sequence parallelism.

Pins the second SP mode to the dense oracle, to ring attention, and through
gradients (jnp and Pallas-interpret paths); plus the transformer model
switch and the head-divisibility contract.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu import ops

N = 8
B, H, D = 2, 8, 16
T_LOCAL = 4
T = N * T_LOCAL


@pytest.fixture(scope="module")
def mesh(cpu_devices):
    return Mesh(np.array(cpu_devices), ("rank",))


def _reference_attention(q, k, v, causal):
    s = np.einsum("bihd,bjhd->bihj", q, k) / np.sqrt(q.shape[-1])
    if causal:
        mask = np.arange(T)[:, None] >= np.arange(T)[None, :]
        s = np.where(mask[None, :, None, :], s, -np.inf)
    p = np.exp(s - s.max(axis=-1, keepdims=True))
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("bihj,bjhd->bihd", p, v)


def _qkv(seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        rng.normal(size=(B, T, H, D)).astype(np.float32) for _ in range(3))


def _run_sharded(fn, mesh, *arrs):
    # sequence axis sharded: [B, T, H, D] -> per-device [B, T/N, H, D]
    sharded = jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=(P(None, "rank"),) * len(arrs),
        out_specs=P(None, "rank")))
    return np.asarray(sharded(*[jnp.asarray(a) for a in arrs]))


@pytest.mark.parametrize("causal", [False, True])
def test_matches_dense_oracle(mesh, causal):
    q, k, v = _qkv()
    out = _run_sharded(
        lambda a, b, c: ops.ulysses_attention(a, b, c, axis="rank",
                                              causal=causal),
        mesh, q, k, v)
    np.testing.assert_allclose(
        out, _reference_attention(q, k, v, causal), rtol=2e-4, atol=2e-5)


def test_matches_ring_attention(mesh):
    q, k, v = _qkv(1)
    ring = _run_sharded(
        lambda a, b, c: ops.ring_attention(a, b, c, axis="rank", causal=True),
        mesh, q, k, v)
    uly = _run_sharded(
        lambda a, b, c: ops.ulysses_attention(a, b, c, axis="rank",
                                              causal=True),
        mesh, q, k, v)
    np.testing.assert_allclose(uly, ring, rtol=2e-4, atol=2e-5)


def test_gradients_match_oracle(mesh):
    q, k, v = _qkv(2)

    def uly_loss(a, b, c):
        out = ops.ulysses_attention(a, b, c, axis="rank", causal=True)
        return jax.lax.psum(jnp.sum(out.astype(jnp.float32) ** 2), "rank")

    grads = jax.jit(jax.shard_map(
        jax.grad(uly_loss, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P(None, "rank"),) * 3, out_specs=(P(None, "rank"),) * 3))(
            *(jnp.asarray(a) for a in (q, k, v)))

    def dense_loss(a, b, c):
        s = jnp.einsum("bihd,bjhd->bihj",
                       a.astype(jnp.float32) / np.sqrt(D),
                       b.astype(jnp.float32))
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bihj,bjhd->bihd", p, c.astype(jnp.float32))
        return jnp.sum(out ** 2)

    expect = jax.grad(dense_loss, argnums=(0, 1, 2))(
        *(jnp.asarray(a) for a in (q, k, v)))
    for g, e in zip(grads, expect):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=5e-4, atol=5e-5)


def test_pallas_path_matches_jnp(mesh):
    q, k, v = _qkv(3)

    def loss(use_pallas):
        def f(a, b, c):
            out = ops.ulysses_attention(
                a, b, c, axis="rank", causal=True, use_pallas=use_pallas,
                pallas_block_q=8)
            return jax.lax.psum(jnp.sum(out.astype(jnp.float32) ** 2), "rank")
        # check_vma=False for BOTH paths: interpret-mode pallas needs it
        # (mixed varying operands, same caveat as test_pallas_attention.py),
        # and without vma the transpose of the loss psum scales cotangents
        # by n — identically in both paths, so the comparison is exact.
        # True-gradient correctness is pinned by test_gradients_match_oracle
        # (vma on, jnp) and the vma-clean compiled TPU path
        # (tests/test_tpu_aot.py::test_ulysses_kernels_lower_for_tpu).
        return jax.jit(jax.shard_map(
            jax.value_and_grad(f, argnums=(0, 1, 2)), mesh=mesh,
            in_specs=(P(None, "rank"),) * 3,
            out_specs=(P(), (P(None, "rank"),) * 3),
            check_vma=False))(
                *(jnp.asarray(a) for a in (q, k, v)))

    (l_j, g_j), (l_p, g_p) = loss(False), loss(True)
    np.testing.assert_allclose(float(l_p), float(l_j), rtol=1e-4)
    for gp, gj in zip(g_p, g_j):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gj),
                                   rtol=5e-3, atol=5e-4)


def test_rejects_uneven_heads(mesh):
    rng = np.random.default_rng(4)
    arrs = tuple(jnp.asarray(
        rng.normal(size=(B, T, 6, D)).astype(np.float32)) for _ in range(3))
    with pytest.raises(ValueError, match="divisible"):
        _run_sharded(
            lambda a, b, c: ops.ulysses_attention(a, b, c, axis="rank"),
            mesh, *arrs)


def test_transformer_sp_mode_switch(mesh):
    """The LM produces (near-)identical logits under either SP mode with the
    same params — the modes are drop-in swaps at the model level."""
    from bluefog_tpu import models

    V, L = 64, 2
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, V, size=(N, B, T_LOCAL)),
        jnp.int32)

    def build(sp_mode):
        return models.RingTransformerLM(
            vocab_size=V, num_layers=L, num_heads=H, d_model=64,
            max_seq_len=T, axis="rank", sp_mode=sp_mode, dtype=jnp.float32)

    m_ring, m_uly = build("ring"), build("ulysses")
    # init with an axis-free twin (identical param tree): ring_attention
    # needs the mesh axis bound, which only exists inside shard_map
    m_init = models.RingTransformerLM(
        vocab_size=V, num_layers=L, num_heads=H, d_model=64,
        max_seq_len=T, axis=None, dtype=jnp.float32)
    params = m_init.init(jax.random.key(0), tokens[0], pos_offset=0)

    def run(model):
        def per_rank(p, tok):
            tok = tok[0]
            off = jax.lax.axis_index("rank") * T_LOCAL
            return model.apply(p, tok, pos_offset=off)[None]
        return np.asarray(jax.jit(jax.shard_map(
            per_rank, mesh=mesh, in_specs=(P(), P("rank")),
            out_specs=P("rank")))(params, tokens))

    np.testing.assert_allclose(run(m_ring), run(m_uly), rtol=1e-4, atol=1e-4)


def test_local_flash_attention_vjp_matches_dense():
    """The exported standalone flash wrapper (and its hand-written VJP) —
    no mesh, no collectives — against the dense oracle."""
    from bluefog_tpu.ops import local_flash_attention
    from bluefog_tpu.ops.ulysses import dense_attention

    rng = np.random.default_rng(11)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 32, 4, 16)).astype(np.float32))
               for _ in range(3))

    for causal in (False, True):
        def loss_flash(a, b, c):
            out = local_flash_attention(
                a, b, c, causal, 1 / np.sqrt(16), 8, True, None)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        def loss_dense(a, b, c):
            out = dense_attention(a, b, c, causal, 1 / np.sqrt(16))
            return jnp.sum(out.astype(jnp.float32) ** 2)

        lf, gf = jax.value_and_grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        ld, gd = jax.value_and_grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(float(lf), float(ld), rtol=1e-5)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
