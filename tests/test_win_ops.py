"""Window op tests (model: reference test/torch_win_ops_test.py)."""
import numpy as np
import jax.numpy as jnp
import pytest

import bluefog_tpu as bf
from bluefog_tpu import topology as tu

N, DIM = 8, 4


@pytest.fixture(autouse=True)
def ctx(cpu_devices):
    bf.init(devices=cpu_devices)
    bf.set_topology(tu.RingGraph(N, connect_style=0), is_weighted=True)
    yield
    bf.win_free()
    bf.shutdown()


def rank_tensor(val_fn=float):
    return jnp.asarray(
        np.broadcast_to(np.array([val_fn(r) for r in range(N)])[:, None], (N, DIM)),
        dtype=jnp.float32)


def test_win_create_update_default_weights():
    """create + put + update with topology weights == neighbor_allreduce."""
    x = rank_tensor()
    assert bf.win_create(x, "w0", zero_init=True)
    bf.win_put(x, "w0")
    out = bf.win_update("w0")
    W = tu.to_weight_matrix(tu.RingGraph(N, connect_style=0))
    expected = (W.T @ np.arange(N, dtype=np.float64))
    for r in range(N):
        np.testing.assert_allclose(
            np.asarray(out[r]), np.full(DIM, expected[r]), rtol=1e-5)


def test_named_window_wire_plumbs_through_registry():
    """The registry layer (bf.win_put/win_get wire=) really reaches the
    compressed delivery path: int8-wired puts land visibly quantized
    values, a wire=None put through the same window is exact (distinct
    jit-cache entries per wire mode), and the update result stays within
    quantization tolerance of the exact combine."""
    x = rank_tensor(lambda r: 0.1 * r + 0.01)
    # independent windows per mode: win_update folds the combine back into
    # the window value, so reusing one window would entangle the modes
    for name in ("wa", "wb", "wc"):
        assert bf.win_create(x, name, zero_init=True)

    bf.win_put(x, "wa")
    exact = np.asarray(bf.win_update("wa"))

    bf.win_put(x, "wb", wire="int8")
    quant = np.asarray(bf.win_update("wb"))
    np.testing.assert_allclose(quant, exact, rtol=0.1, atol=0.02)
    assert not np.array_equal(quant, exact)      # it really quantized

    # the jit cache did not hand the wire="int8" executable back to a
    # wire=None call (same shapes/schedule, different key)
    bf.win_put(x, "wc")
    again = np.asarray(bf.win_update("wc"))
    np.testing.assert_array_equal(again, exact)

    bf.win_get("wc", wire="bf16")
    got = np.asarray(bf.win_update("wc"))
    assert np.isfinite(got).all()


def test_win_update_given_weights():
    x = rank_tensor()
    bf.win_create(x, "w1", zero_init=True)
    bf.win_put(x, "w1")
    out = bf.win_update(
        "w1",
        self_weight=0.5,
        neighbor_weights=[{(r - 1) % N: 0.25, (r + 1) % N: 0.25} for r in range(N)],
    )
    vals = np.arange(N, dtype=np.float64)
    for r in range(N):
        expected = 0.5 * vals[r] + 0.25 * vals[(r - 1) % N] + 0.25 * vals[(r + 1) % N]
        np.testing.assert_allclose(
            np.asarray(out[r]), np.full(DIM, expected), rtol=1e-5)


def test_win_get():
    x = rank_tensor()
    bf.win_create(x, "wg", zero_init=True)
    bf.win_get("wg")
    out = bf.win_update("wg")  # same combine as after a put of win.value
    W = tu.to_weight_matrix(tu.RingGraph(N, connect_style=0))
    expected = W.T @ np.arange(N, dtype=np.float64)
    for r in range(N):
        np.testing.assert_allclose(
            np.asarray(out[r]), np.full(DIM, expected[r]), rtol=1e-5)


def test_win_accumulate_and_collect():
    """Accumulate twice then collect: mailboxes sum, then clear."""
    x = rank_tensor()
    bf.win_create(x, "wa", zero_init=True)
    bf.win_accumulate(x, "wa")
    bf.win_accumulate(x, "wa")
    out = bf.win_update_then_collect("wa")
    vals = np.arange(N, dtype=np.float64)
    for r in range(N):
        expected = vals[r] + 2 * (vals[(r - 1) % N] + vals[(r + 1) % N])
        np.testing.assert_allclose(
            np.asarray(out[r]), np.full(DIM, expected), rtol=1e-5)
    # collected -> mailboxes cleared: another collect returns just the value
    out2 = bf.win_update_then_collect("wa")
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out), rtol=1e-5)


def test_win_put_partial_destinations():
    """dst_weights restricted to a subset of out-neighbors (dynamic put).

    Uses ASYMMETRIC update weights so a put delivered into the wrong mailbox
    slot changes the result (regression: the delivery schedule used to
    recompute slots over the sub-edge set instead of the window's layout).
    """
    x = rank_tensor()
    bf.win_create(x, "wp", zero_init=True)
    # only send clockwise (drop the counter-clockwise edge), scaled by 0.5
    bf.win_put(x, "wp", dst_weights=[{(r + 1) % N: 0.5} for r in range(N)])
    out = bf.win_update(
        "wp", self_weight=0.5,
        neighbor_weights=[{(r - 1) % N: 1.0, (r + 1) % N: 0.0} for r in range(N)])
    vals = np.arange(N, dtype=np.float64)
    for r in range(N):
        # only the clockwise put (from r-1, weight 1.0, scaled 0.5) lands
        expected = 0.5 * vals[r] + 0.5 * vals[(r - 1) % N]
        np.testing.assert_allclose(
            np.asarray(out[r]), np.full(DIM, expected), rtol=1e-5,
            err_msg=f"rank {r}")


def test_win_put_non_edge_rejected():
    x = rank_tensor()
    bf.win_create(x, "we", zero_init=True)
    with pytest.raises(ValueError, match="not an edge"):
        bf.win_put(x, "we", dst_weights=[{(r + 3) % N: 1.0} for r in range(N)])


def test_associated_p_debiasing():
    """With associated-P enabled, a directed (column-substochastic) put
    channel is de-biased by value/p (reference: mpi_win_ops.cc:384-427)."""
    topo = tu.ExponentialTwoGraph(N)
    bf.set_topology(topo)
    rng = np.random.default_rng(3)
    vals = rng.normal(size=(N, DIM)).astype(np.float32)
    x = jnp.asarray(vals)
    # rank-dependent self weights: row-stochastic (mass conserving) but NOT
    # column-stochastic -> plain gossip would be biased; p corrects it
    a = np.linspace(0.2, 0.7, N)
    outs = [tu.GetOutNeighbors(topo, r) for r in range(N)]
    dsts = [{d: (1 - a[r]) / len(outs[r]) for d in outs[r]} for r in range(N)]
    ones_in = [{s: 1.0 for s in tu.GetInNeighbors(topo, r)} for r in range(N)]
    bf.turn_on_win_ops_with_associated_p()
    try:
        bf.win_create(x, "ap", zero_init=True)
        for _ in range(40):
            bf.win_accumulate(x, "ap", dst_weights=dsts)
            x = bf.synchronize(bf.win_update(
                "ap", self_weight=list(a), neighbor_weights=ones_in,
                reset=True))
        p = np.asarray(bf.win_associated_p("ap"))
        assert not np.allclose(p, 1.0)       # the channel is genuinely biased
        np.testing.assert_allclose(p.sum(), N, rtol=1e-4)  # p-mass conserved
        ratio = np.asarray(x) / p[:, None]
        np.testing.assert_allclose(
            ratio, np.tile(vals.mean(axis=0), (N, 1)), atol=1e-3)
    finally:
        bf.turn_off_win_ops_with_associated_p()


def test_win_version_tracking():
    x = rank_tensor()
    bf.win_create(x, "wv", zero_init=True)
    assert bf.get_win_version("wv").sum() == 0
    bf.win_put(x, "wv")
    v = bf.get_win_version("wv")
    assert v.shape == (N, 2)
    assert (v == 1).all()
    bf.win_put(x, "wv")
    assert (bf.get_win_version("wv") == 2).all()
    bf.win_update_then_collect("wv")
    assert bf.get_win_version("wv").sum() == 0


def test_win_mutex_noop():
    x = rank_tensor()
    bf.win_create(x, "wm")
    with bf.win_mutex("wm"):
        bf.win_put(x, "wm")


def test_push_sum_weight_conservation():
    """The associated-P push-sum invariant (reference :780-863): total mass of
    value and of the p-weight lane is conserved each accumulate+collect round,
    and value/p converges to the global average.

    One round = accumulate scale*x to out-neighbors, then
    x <- scale*x + sum(mailboxes) — expressed as a single
    win_update(self_weight=scale, neighbor_weights=1, reset=True).
    """
    topo = tu.ExponentialTwoGraph(N)
    bf.set_topology(topo)
    rng = np.random.default_rng(7)
    vals = rng.normal(size=(N, DIM)).astype(np.float32)
    global_mean = vals.mean(axis=0)

    # extended tensor: [value..., p]  (reference: optimizers.py:1056-1073)
    ext = jnp.concatenate(
        [jnp.asarray(vals), jnp.ones((N, 1), jnp.float32)], axis=1)
    bf.win_create(ext, "ps", zero_init=True)
    total0 = np.asarray(ext).sum(axis=0)

    out_deg = len(tu.GetOutNeighbors(topo, 0))
    scale = 1.0 / (out_deg + 1)
    dsts = [{d: scale for d in tu.GetOutNeighbors(topo, r)} for r in range(N)]
    ones_in = [{s: 1.0 for s in tu.GetInNeighbors(topo, r)} for r in range(N)]

    x = ext
    for _ in range(25):
        bf.win_accumulate(x, "ps", dst_weights=dsts)
        x = bf.synchronize(bf.win_update(
            "ps", self_weight=scale, neighbor_weights=ones_in, reset=True))
        total = np.asarray(x).sum(axis=0)
        np.testing.assert_allclose(total, total0, rtol=1e-4)  # mass conserved

    ratio = np.asarray(x)[:, :DIM] / np.asarray(x)[:, DIM:]
    np.testing.assert_allclose(ratio, np.tile(global_mean, (N, 1)), atol=1e-3)


def test_win_put_wire_codecs(cpu_devices):
    """win_put with wire compression: bf16 matches the uncompressed put to
    cast tolerance; int8 to quantization tolerance; int dtypes reject."""
    import jax
    import pytest
    from jax.sharding import Mesh, PartitionSpec as P

    import bluefog_tpu.topology as tu
    from bluefog_tpu import schedule as sch
    from bluefog_tpu.ops import windows as wops

    n = 8
    sched = sch.compile_topology(tu.ExponentialTwoGraph(n))
    mesh = Mesh(np.array(cpu_devices[:n]), ("rank",))
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(n, 16)), jnp.float32)

    def run(wire):
        def f(xb):
            w = wops.win_create(xb[0], sched)
            w = wops.win_put(w, xb[0], sched, axis="rank", wire=wire)
            return w.recv[None]
        fn = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=P("rank"), out_specs=P("rank")))
        return np.asarray(fn(x))

    exact = run(None)
    np.testing.assert_allclose(run("bf16"), exact, rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(run("int8"), exact, rtol=0.1, atol=0.05)
    assert not np.array_equal(run("bf16"), exact)   # it really quantized

    with pytest.raises(ValueError, match="real float"):
        def fi(xb):
            w = wops.win_create(xb[0], sched)
            return wops.win_put(w, xb[0], sched, axis="rank",
                                wire="bf16").recv[None]
        jax.jit(jax.shard_map(
            fi, mesh=mesh, in_specs=P("rank"), out_specs=P("rank")))(
            jnp.ones((n, 4), jnp.int32))


# ---------------------------------------------------------------------------
# async-gossip satellites: pull-path allocation pin, collect-mask cache,
# named-window staleness stamps
# ---------------------------------------------------------------------------

def _zero_fills(closed_jaxpr, shape):
    """Eqns (recursively) that broadcast a literal 0 into ``shape``."""
    import jax.core as jcore
    hits = []

    def walk(j):
        for eqn in j.eqns:
            if eqn.primitive.name == "broadcast_in_dim":
                inv = eqn.invars[0]
                if (isinstance(inv, jcore.Literal)
                        and np.ndim(inv.val) == 0 and inv.val == 0
                        and tuple(eqn.outvars[0].aval.shape) == shape):
                    hits.append(eqn)
            for v in eqn.params.values():
                items = v if isinstance(v, (list, tuple)) else (v,)
                for u in items:
                    if isinstance(u, jcore.ClosedJaxpr):
                        walk(u.jaxpr)
                    elif isinstance(u, jcore.Jaxpr):
                        walk(u)

    walk(closed_jaxpr.jaxpr)
    return hits


def test_win_pull_skips_window_allocation(monkeypatch):
    """The pull path allocates NO window: no win_create call, no zero-fill
    of the ``[K, ...]`` recv block anywhere in the trace (win_get overwrites
    every slot the combine reads, so the old zero-init was a dead store) —
    and the result still equals the weighted neighbor combine."""
    import jax
    from jax.sharding import PartitionSpec as P
    from bluefog_tpu import schedule as sch
    from bluefog_tpu.ops import windows as wops

    sched = sch.compile_topology(
        tu.RingGraph(N, connect_style=0), weighted=True)
    slots = max(sched.max_in_degree, 1)
    x = rank_tensor()

    def f(xb):
        return wops.win_pull(xb[0], sched)[None]

    def _boom(*a, **k):
        raise AssertionError("win_pull must not allocate a window")

    monkeypatch.setattr(wops, "win_create", _boom)
    sm = jax.shard_map(f, mesh=bf.mesh(), in_specs=P("rank"),
                       out_specs=P("rank"))
    jaxpr = jax.make_jaxpr(sm)(x)
    assert not _zero_fills(jaxpr, (slots, DIM)), (
        "pull path zero-fills its recv block (dead store)")

    out = np.asarray(jax.jit(sm)(x))
    W = tu.to_weight_matrix(tu.RingGraph(N, connect_style=0))
    expected = W.T @ np.arange(N, dtype=np.float64)
    for r in range(N):
        np.testing.assert_allclose(out[r], np.full(DIM, expected[r]),
                                   rtol=1e-5)


def test_collect_masks_cached_per_schedule():
    """The collect combine's unit weight tables are cached per schedule —
    same array OBJECTS on every trace (constant identity is part of the jit
    cache key for donated-carry scans) — and write-protected."""
    from bluefog_tpu import schedule as sch
    from bluefog_tpu.ops import windows as wops

    s1 = sch.compile_topology(tu.ExponentialTwoGraph(N))
    s2 = sch.compile_topology(tu.ExponentialTwoGraph(N))
    a_self, a_slot = wops._collect_masks(s1)
    b_self, b_slot = wops._collect_masks(s1)
    assert a_self is b_self and a_slot is b_slot
    # an equal schedule compiled separately hits the same cache line iff it
    # hashes the same (CommSchedule is frozen/hashable)
    c_self, _ = wops._collect_masks(s2)
    assert (c_self is a_self) == (hash(s1) == hash(s2))
    with pytest.raises(ValueError):
        a_slot[0, 0] = 5.0
    np.testing.assert_allclose(a_self, 1.0)
    K = max(s1.max_in_degree, 1)
    expected = (np.arange(K)[:, None] < s1.in_degree[None, :])
    np.testing.assert_array_equal(a_slot.astype(bool), expected)


def test_win_stamps_and_staleness():
    """Named-window face of the async strategy's per-slot step stamps: a
    full put refreshes every real slot; a partial put ages the slots it
    skipped by exactly one delivery op."""
    x = rank_tensor()
    assert bf.win_create(x, "ws", zero_init=True)
    stamps = bf.get_win_stamps("ws")
    assert stamps.shape[0] == N
    np.testing.assert_array_equal(stamps, 0)
    np.testing.assert_array_equal(bf.win_staleness("ws"), 0)

    bf.win_put(x, "ws")                       # tick 1: every slot stamped
    np.testing.assert_array_equal(bf.win_staleness("ws"), 0)
    real = bf.get_win_stamps("ws") == 1

    # tick 2: clockwise-only put — the counter-clockwise slot ages
    bf.win_put(x, "ws", dst_weights=[{(r + 1) % N: 0.5} for r in range(N)])
    stale = bf.win_staleness("ws")
    assert stale[real].tolist().count(1) == N      # one aged slot per rank
    assert stale[real].tolist().count(0) == N      # one fresh slot per rank
    assert stale[~real].max(initial=0) == 0        # unreal slots report 0

    # the accessor hands out copies, not the live ledger
    view = bf.get_win_stamps("ws")
    view[:] = 99
    assert bf.get_win_stamps("ws").max() <= 2
