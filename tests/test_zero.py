"""ZeRO-1 sharded-optimizer-state strategies.

Beyond-reference capability (the reference replicates optimizer state on
every rank, ``optimizers.py:166-294``): grads reduce-scatter, the local
1/n shard steps, params all-gather.  Oracles: exact trajectory equality
with the replicated strategy (the adapt is elementwise, so sharding it
must be a no-op mathematically), shard-sized state leaves, and hierarchical
convergence with the within-machine-identity invariant.
"""
import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

import bluefog_tpu as bf
from bluefog_tpu import optimizers as bfopt
from bluefog_tpu import topology as tu

N, D = 8, 6


@pytest.fixture(autouse=True)
def ctx(cpu_devices):
    bf.init(devices=cpu_devices, nodes_per_machine=2)
    bf.set_topology(tu.ExponentialTwoGraph(N), is_weighted=True)
    bf.set_machine_topology(tu.RingGraph(N // 2, connect_style=0),
                            is_weighted=True)
    yield
    bf.shutdown()


def _problem(seed=0):
    rng = np.random.default_rng(seed)
    w_star = rng.normal(size=(D,))
    A = rng.normal(size=(N, 20, D))
    b = A @ w_star + 0.1 * rng.normal(size=(N, 20))
    AtA = sum(A[r].T @ A[r] for r in range(N))
    Atb = sum(A[r].T @ b[r] for r in range(N))
    return (jnp.asarray(A, jnp.float32), jnp.asarray(b, jnp.float32),
            np.linalg.solve(AtA, Atb))


def grad_fn(params, batch):
    A, b = batch

    def loss(w):
        r = A @ w["w"] - b
        # the bf16 leaf joins the loss so it carries a real (bf16) gradient,
        # exercising the per-dtype fusion buckets in the ZeRO path
        return jnp.mean(r * r) + 1e-4 * jnp.sum(
            w["w16"].astype(jnp.float32) ** 2)

    return jax.value_and_grad(loss)(params)


def _params():
    return {"w": jnp.zeros((D,), jnp.float32),
            "w16": jnp.ones((5,), jnp.bfloat16)}


def _run(strategy, steps=100, chunk=25, seed=0):
    A, b, w_opt = _problem(seed)
    dist_params = bfopt.replicate(_params())
    dist_state = bfopt.init_distributed(strategy, dist_params)
    step = bfopt.make_train_step(grad_fn, strategy, steps_per_call=chunk)
    batch = jax.tree.map(
        lambda x: jnp.broadcast_to(x[:, None], (N, chunk) + x.shape[1:]),
        (A, b))
    for _ in range(steps // chunk):
        dist_params, dist_state, loss = step(dist_params, dist_state, batch)
        jax.block_until_ready(loss)
    return dist_params, w_opt


def test_zero_matches_gradient_allreduce():
    """Sharding the (elementwise) adapt is exact: same trajectory as the
    replicated strategy, down to float tolerance — including the padded
    bucket (D=6 over 8 ranks pads to 8) and the bf16 bucket."""
    p_zero, w_opt = _run(bfopt.zero_gradient_allreduce(
        optax.adam(0.05)))
    p_full, _ = _run(bfopt.gradient_allreduce(optax.adam(0.05)))
    np.testing.assert_allclose(np.asarray(p_zero["w"]),
                               np.asarray(p_full["w"]), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(
        np.asarray(p_zero["w16"], np.float32),
        np.asarray(p_full["w16"], np.float32), rtol=0.05, atol=0.02)
    # and it actually optimizes
    for r in range(N):
        np.testing.assert_allclose(np.asarray(p_zero["w"])[r], w_opt,
                                   atol=0.05)


def test_zero_state_is_sharded():
    """Optimizer-state leaves hold 1/n of the (padded) parameter count."""
    strat = bfopt.zero_gradient_allreduce(optax.adam(0.05))
    state = strat.init(_params())
    mu = state.opt_state[0].mu           # list of per-dtype shard buffers
    sizes = sorted(leaf.size for leaf in jax.tree.leaves(mu))
    # bf16 bucket: ceil(5/8) -> pad to 8, shard 1; f32 bucket: 6 -> pad 8 -> 1
    assert sizes == [1, 1]
    full = bfopt.gradient_allreduce(optax.adam(0.05)).init(_params())
    full_sizes = sorted(leaf.size
                        for leaf in jax.tree.leaves(full.opt_state[0].mu))
    assert full_sizes == [5, 6]


def test_zero_adapt_with_combine_hierarchical():
    """Machine-level gossip + within-machine ZeRO: converges to the global
    optimum, and every chip in a machine holds identical params (the
    all-gather reassembles one shared update per machine)."""
    comm = bfopt.hierarchical_communicator(bf.machine_schedule())
    strat = bfopt.zero_adapt_with_combine(optax.sgd(0.05), comm)
    dist_params, w_opt = _run(strat, steps=300, chunk=50)
    w = np.asarray(dist_params["w"])
    for r in range(N):
        np.testing.assert_allclose(w[r], w_opt, atol=0.15)
    # rank layout is machine-major (nodes_per_machine=2)
    for m in range(N // 2):
        np.testing.assert_array_equal(w[2 * m], w[2 * m + 1])


def test_zero_single_rank_degenerate(cpu_devices):
    """n=1 mesh: psum_scatter/all_gather are identities; still steps."""
    bf.shutdown()
    bf.init(devices=cpu_devices[:1], nodes_per_machine=1)
    strat = bfopt.zero_gradient_allreduce(optax.sgd(0.1))
    params = {"w": jnp.ones((1, 3), jnp.float32)}
    state = bfopt.init_distributed(strat, params)

    def gf(p, _):
        return jnp.sum(p["w"] ** 2), {"w": 2 * p["w"]}

    step = bfopt.make_train_step(gf, strat)
    params, state, loss = step(params, state, jnp.zeros((1, 1)))
    np.testing.assert_allclose(np.asarray(params["w"]), 0.8 * np.ones((1, 3)))


def test_zero_rejects_tree_coupled_chains():
    """The construction-time tripwire converts the documented elementwise
    requirement into a loud error: chains that couple elements across the
    tree (global-norm clipping, masked/multi_transform) would silently
    diverge from gradient_allreduce under sharding (round-3 review item)."""
    clip_chain = optax.chain(optax.clip_by_global_norm(0.1), optax.sgd(0.05))
    with pytest.raises(ValueError, match="not elementwise"):
        bfopt.zero_gradient_allreduce(clip_chain)
    masked = optax.masked(optax.sgd(0.05), {"w": True, "w16": False})
    with pytest.raises(ValueError, match="not elementwise"):
        bfopt.zero_gradient_allreduce(masked)
    comm = bfopt.hierarchical_communicator(bf.machine_schedule())
    with pytest.raises(ValueError, match="not elementwise"):
        bfopt.zero_adapt_with_combine(clip_chain, comm)
    # the documented escape hatch still constructs
    strat = bfopt.zero_gradient_allreduce(clip_chain, check_elementwise=False)
    assert strat.axes == ("rank",)


def test_zero_rejects_high_threshold_clip():
    """A max_norm ABOVE the base probe's ~2.31 global norm takes the no-op
    branch at probe scale x1 — the x100 magnitude sweep must still catch
    the coupling (round-4 advisor item: the point-probe let these pass)."""
    lazy_clip = optax.chain(optax.clip_by_global_norm(10.0), optax.sgd(0.05))
    with pytest.raises(ValueError, match="not elementwise"):
        bfopt.zero_gradient_allreduce(lazy_clip)


def test_zero_tripwire_passes_elementwise_chains():
    """sgd/momentum/adam/adamw construct cleanly (and the equivalence test
    above keeps pinning that they are exact under sharding)."""
    for opt in (optax.sgd(0.05), optax.sgd(0.05, momentum=0.9),
                optax.adam(1e-3), optax.adamw(1e-3)):
        bfopt.zero_gradient_allreduce(opt)


def test_zero_local_axis_plumbs_2d_mesh():
    """zero_gradient_allreduce(axis='local'): per-machine synchronous DP
    with no cross-machine traffic — the strategy must carry the 2-D axes so
    make_train_step builds the machine x local mesh (round-2 review fix)."""
    strat = bfopt.zero_gradient_allreduce(optax.sgd(0.05), axis="local")
    assert strat.axes == ("machine", "local")
    dist_params, _ = _run(strat, steps=25, chunk=25)
    w = np.asarray(dist_params["w"])
    assert np.isfinite(w).all()
    for m in range(N // 2):            # identical within each machine...
        np.testing.assert_array_equal(w[2 * m], w[2 * m + 1])
    # ...but machines see different data shards, so they diverge
    assert not np.allclose(w[0], w[2])
