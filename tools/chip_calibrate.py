"""Chip calibration: measured matmul FLOP rate and HBM bandwidth.

Establishes the *achievable* ceilings on the attached accelerator — the
denominators that make MFU and bandwidth-utilization claims in
docs/PERFORMANCE.md concrete.  Also the regression probe for the timing
methodology: if the reported TFLOP/s exceeds the device's spec sheet, the
synchronization barrier is broken (see ``bf.hard_sync`` — on the axon PJRT
plugin ``block_until_ready`` returns at dispatch, which once produced a
"28 PFLOP/s matmul" here).

Run:  python tools/chip_calibrate.py        (single client on the tunnel)
Prints one JSON line per probe.
"""
import json
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
from bluefog_tpu.api import hard_sync  # noqa: E402


def main():
    d = jax.devices()[0]
    print(json.dumps({"probe": "device", "kind": d.device_kind,
                      "platform": d.platform}))

    for n in (4096, 8192):
        a = jnp.ones((n, n), jnp.bfloat16)
        f = jax.jit(lambda a, b: a @ b)
        c = hard_sync(f(a, a))
        iters = 50
        t0 = time.perf_counter()
        for _ in range(iters):
            c = f(a, c)           # chained: no inter-call overlap ambiguity
        hard_sync(c)
        dt = (time.perf_counter() - t0) / iters
        print(json.dumps({
            "probe": f"matmul_bf16_{n}", "ms": round(dt * 1e3, 3),
            "tflops": round(2 * n ** 3 / dt / 1e12, 1)}))

    x = jnp.ones((2 ** 28,), jnp.float32)          # 1 GiB
    g = jax.jit(lambda x: x * 1.0001)
    y = hard_sync(g(x))
    t0 = time.perf_counter()
    for _ in range(20):
        y = g(y)
    hard_sync(y)
    dt = (time.perf_counter() - t0) / 20
    print(json.dumps({"probe": "hbm_rw_1GiB", "ms": round(dt * 1e3, 3),
                      "gbps": round(2 * 2 ** 30 / dt / 1e9)}))


if __name__ == "__main__":
    main()
