"""Chip calibration: measured matmul FLOP rate and HBM bandwidth.

Establishes the *achievable* ceilings on the attached accelerator — the
denominators that make MFU and bandwidth-utilization claims in
docs/PERFORMANCE.md concrete.  Also the regression probe for the timing
methodology: if the reported TFLOP/s exceeds the device's spec sheet, the
synchronization barrier is broken (see ``bf.hard_sync`` — on the axon PJRT
plugin ``block_until_ready`` returns at dispatch, which once produced a
"28 PFLOP/s matmul" here).

Each probe loops its body inside ONE compiled program (``lax.scan``), so a
single host->device dispatch covers the whole timed region: round-2's
per-dispatch HBM probe measured 307 GB/s on an 819 GB/s part because ~ms of
tunnel dispatch latency was charged to every 1 GiB copy.  The per-dispatch
variant is still measured alongside — the DIFFERENCE is the per-call
dispatch overhead, the number that justifies ``steps_per_call`` batching in
bench.py.

Run:  python tools/chip_calibrate.py          (single client on the tunnel)
      python tools/chip_calibrate.py --smoke  (tiny shapes, any backend)
Prints one JSON line per probe.
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, ".")
from bluefog_tpu.api import hard_sync  # noqa: E402
from bluefog_tpu.utils.config import enable_compilation_cache  # noqa: E402


def _bench_mod():
    """bench.py holds the chip spec tables (single source for every
    tool's denominators); its top level is stdlib-only so the import is
    side-effect free."""
    import os
    repo = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir)
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import bench
    return bench


def _spec_peak_tflops(device_kind: str):
    peak = _bench_mod()._peak_flops(device_kind)
    return peak / 1e12 if peak else None


def _timed(f, x):
    """Seconds for one dispatch of compiled ``f`` (hard_sync barrier)."""
    t0 = time.perf_counter()
    hard_sync(f(x))
    return time.perf_counter() - t0


def _scanned(body, x, iters):
    """One-dispatch seconds-per-iteration of ``body`` via lax.scan."""
    f = jax.jit(lambda x0: lax.scan(
        lambda c, _: (body(c), None), x0, None, length=iters)[0])
    hard_sync(f(x))                       # compile + warm
    return _timed(f, x) / iters


def _dispatched(body, x, iters):
    """Per-iteration seconds with one host dispatch per call (the naive
    loop); the gap vs _scanned is the per-dispatch overhead."""
    f = jax.jit(body)
    y = hard_sync(f(x))
    t0 = time.perf_counter()
    for _ in range(iters):
        y = f(y)                          # chained: no overlap ambiguity
    hard_sync(y)
    return (time.perf_counter() - t0) / iters


def main():
    smoke = "--smoke" in sys.argv
    if smoke:
        # the axon plugin force-sets jax_platforms at interpreter boot,
        # overriding the env var — without this a CI smoke dials the tunnel
        jax.config.update("jax_platforms", "cpu")
    enable_compilation_cache()      # after the platform pin: no-op on CPU
    d = jax.devices()[0]
    print(json.dumps({"probe": "device", "kind": d.device_kind,
                      "platform": d.platform}))

    mm_sizes = (256,) if smoke else (4096, 8192)
    iters = 5 if smoke else 50
    peak = _spec_peak_tflops(d.device_kind)
    mm_rows = []
    for n in mm_sizes:
        # random ROW-STOCHASTIC operand: rows sum to 1, so the scan carry
        # stays O(1) across 50 chained matmuls — and, unlike the obvious
        # jnp.full(1/n) splat, it is not a broadcast-of-scalar that XLA's
        # algebraic simplifier rewrites into an O(n^2) column reduction
        # (that rewrite once reported an impossible 641 TF/s here on a
        # 197 TF/s chip: the "matmul" never touched the MXU)
        a = jax.random.uniform(jax.random.key(n), (n, n), jnp.float32,
                               0.5, 1.5)
        a = (a / a.sum(axis=1, keepdims=True)).astype(jnp.bfloat16)
        per_scan = _scanned(lambda c: a @ c, a, iters)
        per_call = _dispatched(lambda c: a @ c, a, iters)
        tflops = 2 * n ** 3 / per_scan / 1e12
        row = {
            "probe": f"matmul_bf16_{n}",
            "ms": round(per_scan * 1e3, 3),
            "tflops": round(tflops, 1),
            "per_dispatch_ms": round(per_call * 1e3, 3),
            "dispatch_overhead_ms": round((per_call - per_scan) * 1e3, 3)}
        if peak:
            row["spec_peak_tflops"] = round(peak, 1)
            # a rate above the spec sheet means the MEASUREMENT is broken
            # (folded operand or a sync barrier that returned at dispatch),
            # never that the chip overachieved — flag it loudly
            if tflops > peak:
                row["suspect"] = True
                row["note"] = (f"{tflops:.1f} TF/s exceeds the "
                               f"{peak:.0f} TF/s spec peak: the operand was "
                               "folded or the sync barrier returned early")
        else:
            # the trust criterion documented in docs/PERFORMANCE.md is
            # "carries spec_peak_tflops" — say WHY it is absent rather
            # than silently skipping the check
            row["spec_peak_tflops"] = None
            row["note"] = (f"device kind {d.device_kind!r} not in "
                           "bench.PEAK_FLOPS: above-peak check skipped")
        mm_rows.append(row)
    # structural cross-check BEFORE printing: a real n^3 matmul takes ~8x
    # longer at 2n.  A folded operand (O(n^2) reduction) or broken barrier
    # flattens the ratio — the pre-fix splat showed 8192 at 1.04x the 4096
    # time while the 4096 rate sat BELOW peak, which the above-peak check
    # alone misses.
    if len(mm_rows) == 2:
        ratio = mm_rows[1]["ms"] / max(mm_rows[0]["ms"], 1e-9)
        if ratio < 4.0:
            msg = (f"time({mm_sizes[1]})/time({mm_sizes[0]}) "
                   f"= {ratio:.2f}x, expected ~8x for a real "
                   "O(n^3) matmul: operand folding or "
                   "early-return barrier")
            for row in mm_rows:
                row["suspect"] = True
                # append: an above-peak diagnosis already in the note is
                # the stronger evidence and must survive
                row["note"] = (row["note"] + "; " + msg
                               if row.get("note") else msg)
    for row in mm_rows:
        print(json.dumps(row))

    hbm_sizes = (2 ** 20,) if smoke else (2 ** 27, 2 ** 28)   # 512MiB, 1GiB
    hbm_peak = _bench_mod()._peak_hbm_gbps(d.device_kind)
    for size in hbm_sizes:
        x = jnp.ones((size,), jnp.float32)
        bytes_per_iter = 2 * 4 * size                  # read + write, f32
        per_scan = _scanned(lambda y: y * 1.0001, x, iters)
        per_call = _dispatched(lambda y: y * 1.0001, x, iters)
        gbps = bytes_per_iter / per_scan / 1e9
        row = {
            "probe": f"hbm_rw_{4 * size // 2 ** 20}MiB",
            "ms": round(per_scan * 1e3, 3),
            "gbps": round(gbps),
            "per_dispatch_gbps": round(bytes_per_iter / per_call / 1e9),
            "dispatch_overhead_ms": round((per_call - per_scan) * 1e3, 3)}
        if hbm_peak:
            row["spec_peak_gbps"] = hbm_peak
            # same logic as the matmul flag: above-spec bandwidth means a
            # broken barrier (returned at dispatch) or a folded body
            if gbps > hbm_peak:
                row["suspect"] = True
                row["note"] = (f"{gbps:.0f} GB/s exceeds the {hbm_peak} "
                               "GB/s spec peak: the sync barrier returned "
                               "early or the probe body was folded")
        else:
            row["spec_peak_gbps"] = None
            row["note"] = (f"device kind {d.device_kind!r} not in "
                           "bench.HBM_PEAK_GBPS: above-peak check skipped")
        print(json.dumps(row))


if __name__ == "__main__":
    main()
