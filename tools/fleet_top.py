"""fleet_top: live terminal dashboard over the gossiped fleet view.

Any rank running with a fleet view armed (``BLUEFOG_FLEET_EVERY=K`` /
``bfrun-tpu --fleet-view K``) and the metrics HTTP server up
(``--metrics-port``) serves its view of the *whole fleet* at ``/fleet``
— per-rank step time, consensus distance, queue depth, SLO burn,
hot-expert skew, and the staleness age of every row.  This tool renders
that JSON as a ranks × signals table with a refresh loop; because the
view is gossiped, pointing it at ANY rank shows the whole fleet.

Sources (one required):
    --url http://host:port/fleet    scrape a live rank
    --from-file fleet.json          render a saved view
    --virtual-cpu                   self-contained 8-virtual-rank CPU
                                    estate: trains a few steps with the
                                    carrier armed, scrapes its own /fleet
                                    over HTTP (the CI/battery path)

Modes:
    (default)                       refresh loop (--interval seconds)
    --once                          one frame, then exit
    --once --json [--out f.json]    machine-readable frame for CI: the
                                    raw /fleet JSON, schema-checked

Exit codes: 0 ok; 1 source unreachable / not armed / bad schema.
"""
import argparse
import json
import os
import sys
import time
import urllib.request

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SCHEMA = "bluefog-fleet-1"

# dashboard columns: (header, metric name, format)
COLUMNS = (
    ("step_s", "bluefog_step_time_ewma_s", "{:.4f}"),
    ("consens", "bluefog_consensus_distance_max", "{:.2e}"),
    ("stale", "bluefog_async_staleness_steps", "{:.0f}"),
    ("queue", "bluefog_serve_queue_depth", "{:.0f}"),
    ("p99_s", "bluefog_serve_p99_s", "{:.4f}"),
    ("burn", "bluefog_slo_burn_rate", "{:.2f}"),
    ("hot_exp", "bluefog_serve_hot_expert_fraction", "{:.2f}"),
)


def check_schema(doc):
    """Raise ValueError unless ``doc`` looks like a /fleet frame (the CI
    schema assert)."""
    if not isinstance(doc, dict):
        raise ValueError("fleet frame is not a JSON object")
    if doc.get("schema") != SCHEMA:
        raise ValueError(f"schema {doc.get('schema')!r} != {SCHEMA!r}")
    for key in ("n", "round", "live_ranks", "staleness", "metrics"):
        if key not in doc:
            raise ValueError(f"fleet frame missing key {key!r}")
    st = doc["staleness"]
    for key in ("rounds_per_rank", "rounds_max", "bound_rounds"):
        if key not in st:
            raise ValueError(f"fleet staleness missing key {key!r}")
    for name, m in doc["metrics"].items():
        if "kind" not in m:
            raise ValueError(f"metric {name!r} missing kind")
        if m["kind"] != "histogram" and "per_rank" not in m:
            raise ValueError(f"metric {name!r} missing per_rank table")
    return doc


def _per_rank(doc, name, rank):
    m = doc.get("metrics", {}).get(name)
    if not m or m.get("kind") == "histogram":
        return None
    per = m.get("per_rank", {})
    # JSON object keys are strings; in-process dicts use ints
    return per.get(str(rank), per.get(rank))


def render(doc):
    """One frame as text: header + ranks × signals table."""
    st = doc["staleness"]
    ages = st.get("rounds_per_rank") or []
    dead = set(doc.get("dead_ranks", ()))
    lines = [
        f"fleet_top — {len(doc['live_ranks'])}/{doc['n']} ranks live, "
        f"round {doc['round']}, view of rank {doc.get('rank', '?')}",
        f"staleness: max {st.get('rounds_max')} rounds "
        f"(bound {st.get('bound_rounds')}), "
        f"probe cadence {_fmt(st.get('probe_cadence_s'), '{:.3f}')}s, "
        f"age est {_fmt(st.get('age_s_est'), '{:.3f}')}s",
        "",
    ]
    headers = ["rank"] + [h for h, _, _ in COLUMNS] + ["age", ""]
    rows = [headers]
    for r in range(int(doc["n"])):
        cells = [str(r)]
        for _, name, fmt in COLUMNS:
            cells.append(_fmt(_per_rank(doc, name, r), fmt))
        age = ages[r] if r < len(ages) else None
        cells.append(_fmt(age, "{:.0f}"))
        cells.append("DEAD" if r in dead else "")
        rows.append(cells)
    widths = [max(len(row[i]) for row in rows) for i in range(len(headers))]
    for i, row in enumerate(rows):
        lines.append("  ".join(c.rjust(w) for c, w in zip(row, widths)))
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    global_bits = []
    for name, m in sorted(doc.get("metrics", {}).items()):
        if m.get("kind") == "counter" and m.get("global") is not None:
            short = name[len("bluefog_"):] if name.startswith("bluefog_") \
                else name
            global_bits.append(f"{short}={m['global']:g}")
    if global_bits:
        lines += ["", "fleet totals: " + "  ".join(global_bits)]
    return "\n".join(lines)


def _fmt(v, fmt):
    if v is None:
        return "-"
    try:
        return fmt.format(float(v))
    except (TypeError, ValueError):
        return str(v)


def fetch(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


# ---------------------------------------------------------------------------
# --virtual-cpu: the self-contained estate (CI smoke / hw_watch battery)
# ---------------------------------------------------------------------------

def _self_estate(n=8, steps=6, every=1):
    """Spin an n-virtual-rank CPU estate, train ``steps`` gossip steps
    with the fleet carrier armed, serve /fleet over HTTP, and return
    (frame fetched over HTTP, invariants dict)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    sys.path.insert(0, REPO)
    import bluefog_tpu as bf
    from bluefog_tpu import optimizers as bfopt
    from bluefog_tpu import topology as tu
    from bluefog_tpu.utils import fleetview as bffleet
    from bluefog_tpu.utils import metrics as bfm

    bf.init(devices=jax.devices()[:n])
    bf.set_topology(tu.ExponentialTwoGraph(n), is_weighted=True)
    bffleet.arm(every=every)
    port = bfm.start_http_server(0)

    d = 16

    def grad_fn(params, batch):
        loss = jnp.mean((params["w"] - batch) ** 2)
        return loss, jax.grad(
            lambda p: jnp.mean((p["w"] - batch) ** 2))(params)

    strat = bfopt.adapt_with_combine(
        optax.sgd(0.0), bfopt.neighbor_communicator(bf.static_schedule()))
    params = {"w": jnp.broadcast_to(
        jnp.arange(float(n))[:, None], (n, d)).astype(jnp.float32)}
    state = bfopt.init_distributed(strat, params)
    step = bfopt.make_train_step(grad_fn, strat)   # cadence from the arm
    batch = jnp.zeros((n, d), jnp.float32)
    for _ in range(steps):
        params, state, loss = step(params, state, batch)
    jax.block_until_ready(loss)

    frame = fetch(f"http://127.0.0.1:{port}/fleet")
    health = fetch(f"http://127.0.0.1:{port}/healthz")
    invariants = {
        "retraces_after_warmup": bfm.counter(
            "bluefog_retrace_after_warmup_total").total(),
        "healthz_ok": health.get("status") == "ok",
        "fleet_armed": bool(health.get("fleet_armed")),
        "train_steps": steps,
    }
    bfm.stop_http_server()
    bf.shutdown()
    return frame, invariants


def main():
    ap = argparse.ArgumentParser(
        description="Live terminal dashboard over the gossiped fleet view.")
    src = ap.add_mutually_exclusive_group()
    src.add_argument("--url", default=None,
                     help="a live rank's /fleet endpoint "
                          "(http://host:port/fleet)")
    src.add_argument("--from-file", default=None,
                     help="render a saved /fleet JSON instead of scraping")
    src.add_argument("--virtual-cpu", action="store_true",
                     help="self-contained 8-virtual-rank CPU estate "
                          "(trains briefly, scrapes its own /fleet)")
    ap.add_argument("--once", action="store_true",
                    help="one frame, then exit (no refresh loop)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the raw fleet JSON (schema-checked) "
                         "instead of the table")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2)")
    ap.add_argument("--frames", type=int, default=None,
                    help="stop after this many frames (default: forever)")
    ap.add_argument("--out", default=None,
                    help="also write the last frame's JSON here")
    args = ap.parse_args()
    if not (args.url or args.from_file or args.virtual_cpu):
        ap.error("give --url, --from-file, or --virtual-cpu")
    if args.virtual_cpu and not args.once:
        args.once = True                # the self-estate is one-shot

    invariants = None

    def get_frame():
        if args.from_file:
            with open(args.from_file) as f:
                return json.load(f)
        return fetch(args.url)

    try:
        if args.virtual_cpu:
            frame, invariants = _self_estate()
        else:
            frame = get_frame()
        check_schema(frame)
    except Exception as e:
        print(json.dumps({"ok": False,
                          "error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(1)

    frames = 0
    while True:
        doc = dict(frame)
        if invariants is not None:
            doc["invariants"] = invariants
            doc["ok"] = (invariants["retraces_after_warmup"] == 0
                         and invariants["healthz_ok"])
        if args.as_json:
            print(json.dumps(doc))
        else:
            if not args.once:
                print("\033[2J\033[H", end="")       # clear + home
            print(render(frame))
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(doc, f, indent=1)
        frames += 1
        if args.once or (args.frames is not None and frames >= args.frames):
            break
        try:
            time.sleep(args.interval)
            frame = check_schema(get_frame())
        except KeyboardInterrupt:
            break
        except Exception as e:
            print(f"fleet_top: source lost: {e}", file=sys.stderr)
            sys.exit(1)
    sys.exit(0)


if __name__ == "__main__":
    main()
