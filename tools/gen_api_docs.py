"""Generate docs/API.md from the package's docstrings.

The counterpart of the reference's sphinx tree (``docs/*.rst``): one
markdown file covering the public surface, cross-linked to the reference
names documented in ``docs/PARITY.md``.  Regenerate after API changes:

    python tools/gen_api_docs.py
"""
import inspect
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

MODULES = [
    ("bluefog_tpu.api", "Core API (init, ops, synchronization)"),
    ("bluefog_tpu.topology", "Topologies (static + dynamic generators)"),
    ("bluefog_tpu.schedule", "Communication schedules (topology compiler)"),
    ("bluefog_tpu.optimizers", "Distributed optimizer strategies"),
    ("bluefog_tpu.ops.collectives", "Collective ops (gossip primitives)"),
    ("bluefog_tpu.ops.windows", "Window ops (one-sided mailboxes)"),
    ("bluefog_tpu.ops.ring", "Ring attention (sequence parallelism)"),
    ("bluefog_tpu.ops.ulysses", "Ulysses attention (all-to-all SP)"),
    ("bluefog_tpu.ops.pallas_attention", "Pallas flash-attention kernels"),
    ("bluefog_tpu.ops.pallas_decode", "Paged flash-decode kernel (serving)"),
    ("bluefog_tpu.parallel.context", "Mesh context (init/topology state)"),
    ("bluefog_tpu.parallel.exec_cache",
     "Warm executable pool (recompile-free regrowth)"),
    ("bluefog_tpu.parallel.windows", "Window registry (named windows)"),
    ("bluefog_tpu.parallel.pipeline", "Pipeline parallelism"),
    ("bluefog_tpu.parallel.compose",
     "Composed parallelism (gossip-DP x PP x TP x Ulysses x EP)"),
    ("bluefog_tpu.parallel.tensor_parallel", "Tensor parallelism"),
    ("bluefog_tpu.parallel.expert", "Expert (MoE) parallelism"),
    ("bluefog_tpu.moe.layers", "Routed-MoE layers (router + expert FFN)"),
    ("bluefog_tpu.moe.model", "Routed-MoE reference LM"),
    ("bluefog_tpu.checkpoint", "Checkpointing (orbax, elastic, async)"),
    ("bluefog_tpu.serve.engine", "Serving engine (prefill + fused decode)"),
    ("bluefog_tpu.serve.kv_cache", "Slotted paged KV cache"),
    ("bluefog_tpu.serve.scheduler", "Continuous batching scheduler"),
    ("bluefog_tpu.serve.refresh", "Live gossip weight refresh"),
    ("bluefog_tpu.data", "Sharded input pipeline"),
    ("bluefog_tpu.fusion", "Tensor fusion (per-dtype bucketing)"),
    ("bluefog_tpu.models", "Model zoo"),
    ("bluefog_tpu.run.launcher", "bfrun-tpu launcher"),
    ("bluefog_tpu.run.interactive", "Interactive multi-host mode"),
    ("bluefog_tpu.utils.utility", "Broadcast utilities (restart flow)"),
    ("bluefog_tpu.utils.torch_compat", "PyTorch migration helpers"),
    ("bluefog_tpu.utils.tf_compat", "TensorFlow/Keras migration helpers"),
    ("bluefog_tpu.utils.config", "Environment configuration"),
    ("bluefog_tpu.utils.timeline", "Timeline tracing"),
    ("bluefog_tpu.utils.metrics", "Live metrics registry + exporters"),
    ("bluefog_tpu.utils.tracing", "Request-scoped span tracing"),
    ("bluefog_tpu.utils.timeseries", "Bounded metric history rings"),
    ("bluefog_tpu.utils.fleetview",
     "Fleet view (gossiped whole-fleet metric carrier)"),
    ("bluefog_tpu.diagnostics", "Consensus-health probes + peer health"),
    ("bluefog_tpu.utils.watchdog", "Stall watchdog"),
    ("bluefog_tpu.resilience", "Fault tolerance (healing + rollback)"),
    ("bluefog_tpu.utils.chaos", "Deterministic fault injection"),
    ("bluefog_tpu.autotune.tuner", "Strategy autotuner (bf.autotune)"),
    ("bluefog_tpu.autotune.plan", "Autotune plans (persist/apply/replay)"),
    ("bluefog_tpu.autotune.candidates", "Autotune candidate enumeration"),
    ("bluefog_tpu.autotune.cost_model", "Autotune analytic cost model"),
    ("bluefog_tpu.autotune.bank", "Autotune measurement bank (tier 2)"),
    ("bluefog_tpu.autotune.trials", "Autotune live micro-trials (tier 3)"),
    ("bluefog_tpu.utils.hlo_bytes", "Wire-byte accounting from HLO"),
]


def _strip_addrs(text):
    import re
    # repr'd default objects embed memory addresses — nondeterministic
    # churn on every regeneration (signatures AND dataclass auto-docstrings)
    return re.sub(r" at 0x[0-9a-f]+", "", text)


def _doc_head(obj, max_paras=1):
    doc = inspect.getdoc(obj)
    if not doc:
        return "*(no docstring)*"
    paras = doc.split("\n\n")
    return _strip_addrs("\n\n".join(paras[:max_paras]).strip())


def _signature(obj):
    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    return _strip_addrs(sig)


def _members(mod):
    names = getattr(mod, "__all__", None)
    out = []
    for name in names if names else sorted(vars(mod)):
        if name.startswith("_"):
            continue
        obj = getattr(mod, name, None)
        if obj is None or inspect.ismodule(obj):
            continue
        defined_here = getattr(obj, "__module__", None) == mod.__name__
        if not (names or defined_here):
            continue   # without __all__, skip re-exports
        if inspect.isfunction(obj) or inspect.isclass(obj):
            out.append((name, obj))
    return out


def main():
    import importlib

    lines = [
        "# API reference",
        "",
        "Generated from docstrings by `tools/gen_api_docs.py` — do not edit",
        "by hand.  Reference-name cross-links: `docs/PARITY.md`; design",
        "rationale: `docs/DESIGN.md`; measured numbers:",
        "`docs/PERFORMANCE.md`.",
        "",
        "Most names are re-exported at the top level: `import bluefog_tpu as",
        "bf; bf.neighbor_allreduce(...)`, `bf.optimizers.*`, `bf.topology.*`.",
        "",
    ]
    toc = ["## Contents", ""]
    body = []
    for mod_name, title in MODULES:
        mod = importlib.import_module(mod_name)
        anchor = mod_name.replace(".", "")
        toc.append(f"- [`{mod_name}` — {title}](#{anchor})")
        body += [f'<a name="{anchor}"></a>', "", f"## `{mod_name}` — {title}",
                 ""]
        mod_doc = _doc_head(mod, max_paras=1)
        if mod_doc != "*(no docstring)*":
            body += [mod_doc, ""]
        for name, obj in _members(mod):
            if inspect.isclass(obj):
                body += [f"### `{name}`", "", _doc_head(obj, 2), ""]
                methods = [
                    (n, m) for n, m in inspect.getmembers(obj)
                    if not n.startswith("_")
                    and (inspect.isfunction(m) or inspect.ismethod(m))
                    and m.__qualname__.startswith(obj.__name__ + ".")]
                for mname, meth in methods:
                    body += [f"- **`.{mname}{_signature(meth)}`** — "
                             f"{_doc_head(meth, 1)}"]
                if methods:
                    body.append("")
            else:
                body += [f"### `{name}{_signature(obj)}`", "",
                         _doc_head(obj, 2), ""]
    out = "\n".join(lines + toc + [""] + body).rstrip() + "\n"
    path = os.path.join(os.path.dirname(__file__), os.pardir, "docs",
                        "API.md")
    with open(path, "w") as f:
        f.write(out)
    print(f"wrote {os.path.normpath(path)} "
          f"({len(out.splitlines())} lines, {len(MODULES)} modules)")


if __name__ == "__main__":
    main()
