"""Gossip-step microbenchmark: schedule quality + per-step cost by topology.

Prints, for each topology family at a given size: the number of compiled
ppermute rounds (the latency chain), the bytes each chip moves per step
relative to model size (the bandwidth cost), and the measured wall-clock per
gossip step on the current backend.  The rounds/bytes columns are the
hardware-independent quality of the schedule compiler; the ms column is
backend-specific (virtual CPU mesh here, ICI on TPU).

Run: python tools/gossip_bench.py --virtual-cpu --params 1048576
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--virtual-cpu", action="store_true")
    parser.add_argument("--params", type=int, default=1 << 20,
                        help="elements per rank in the gossip buffer")
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--train-step", action="store_true",
                        help="also compare full CTA train-step time by "
                             "communicator: fused / unfused / empty / "
                             "allreduce (overlap + fusion cost on this "
                             "backend)")
    args = parser.parse_args()

    if args.virtual_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    import jax
    if args.virtual_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    import bluefog_tpu as bf
    from bluefog_tpu import schedule as sch
    from bluefog_tpu import topology as tu

    bf.init(platform="cpu" if args.virtual_cpu else None)
    n = bf.size()

    topologies = {
        "ring": tu.RingGraph(n),
        "expo2": tu.ExponentialTwoGraph(n),
        "mesh2d": tu.MeshGrid2DGraph(n),
        "star": tu.StarGraph(n),
        "full": tu.FullyConnectedGraph(n),
    }
    dyn = sch.compile_dynamic_schedules(
        lambda r: tu.GetDynamicOnePeerSendRecvRanks(
            tu.ExponentialTwoGraph(n), r), n)

    x = jnp.ones((n, args.params), jnp.float32)
    rows = []

    def measure(schedule):
        fn = jax.jit(jax.shard_map(
            lambda t: bf.ops.neighbor_allreduce(t[0], schedule)[None],
            mesh=bf.mesh(), in_specs=P("rank"), out_specs=P("rank")))
        out = bf.hard_sync(fn(x))
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(out)
        bf.hard_sync(out)
        return (time.perf_counter() - t0) / args.iters * 1e3

    for name, topo in topologies.items():
        s = sch.compile_topology(topo, weighted=True)
        send_deg = float(np.mean(s.out_degree))
        rows.append((name, s.num_rounds, send_deg, measure(s)))
    rows.append(("expo2-dynamic(1peer)", dyn[0].num_rounds,
                 float(np.mean(dyn[0].out_degree)), measure(dyn[0])))
    # the allreduce comparison line (Horovod-mode)
    fn = jax.jit(jax.shard_map(
        lambda t: bf.ops.allreduce(t[0])[None],
        mesh=bf.mesh(), in_specs=P("rank"), out_specs=P("rank")))
    out = bf.hard_sync(fn(x))
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = fn(out)
    bf.hard_sync(out)
    ar_ms = (time.perf_counter() - t0) / args.iters * 1e3

    print(f"{n} devices, {args.params} f32/rank "
          f"({args.params * 4 / 2**20:.1f} MiB model):")
    print(f"{'topology':>22} {'rounds':>7} {'x model sent/step':>18} {'ms/step':>9}")
    for name, rounds, deg, ms in rows:
        print(f"{name:>22} {rounds:>7} {deg:>18.2f} {ms:>9.2f}")
    print(f"{'global allreduce':>22} {'-':>7} {2 * (n - 1) / n:>18.2f} {ar_ms:>9.2f}")

    if args.train_step:
        _train_step_comparison(args, bf, n)


def _train_step_comparison(args, bf, n):
    """Full CTA train step (MLP, scan of 4) under different communicators.

    The empty-communicator row is the pure-compute floor; the gap between it
    and the gossip rows is the *visible* (non-overlapped) communication cost
    on this backend.  On TPU the async start/done scheduling hides most of it
    (tests/test_tpu_aot.py proves the schedule); the virtual CPU mesh runs
    collectives synchronously, so CPU gaps are an upper bound.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from bluefog_tpu import optimizers as bfopt
    from bluefog_tpu import topology as tu

    bf.set_topology(tu.ExponentialTwoGraph(n))
    dim, bsz, steps = 256, 32, 4

    def grad_fn(params, batch):
        x, y = batch
        def loss(p):
            h = jnp.tanh(x @ p["w1"])
            return jnp.mean((h @ p["w2"] - y) ** 2)
        return jax.value_and_grad(loss)(params)

    comms = {
        "gossip fused": bfopt.neighbor_communicator(bf.static_schedule()),
        "gossip unfused": bfopt.neighbor_communicator(
            bf.static_schedule(), fuse=False),
        "no comm (floor)": bfopt.empty_communicator(),
        "global allreduce": bfopt.allreduce_communicator(),
    }
    print(f"\nCTA train step (MLP {dim}x{dim}x2, batch {bsz}, scan {steps}) "
          f"by communicator:")
    print(f"{'communicator':>22} {'ms/step':>9}")
    for name, comm in comms.items():
        strat = bfopt.adapt_with_combine(optax.sgd(0.01), comm)
        params = bfopt.replicate({"w1": jnp.zeros((dim, dim)),
                                  "w2": jnp.zeros((dim, dim))})
        state = bfopt.init_distributed(strat, params)
        step = bfopt.make_train_step(grad_fn, strat, steps_per_call=steps)
        batch = tuple(jnp.zeros((n, steps, bsz, dim)) for _ in range(2))
        params, state, loss = step(params, state, batch)   # compile
        bf.hard_sync(loss)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            params, state, loss = step(params, state, batch)
        bf.hard_sync(loss)
        ms = (time.perf_counter() - t0) / (args.iters * steps) * 1e3
        print(f"{name:>22} {ms:>9.2f}")


if __name__ == "__main__":
    main()
