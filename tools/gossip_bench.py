"""Gossip-step microbenchmark: schedule quality + per-step cost by topology.

Prints, for each topology family at a given size: the number of compiled
ppermute rounds (the latency chain), the bytes each chip moves per step
relative to model size (the bandwidth cost), and the measured wall-clock per
gossip step on the current backend.  The rounds/bytes columns are the
hardware-independent quality of the schedule compiler; the ms column is
backend-specific (virtual CPU mesh here, ICI on TPU).

Run: python tools/gossip_bench.py --virtual-cpu --params 1048576

``--frontier`` switches to the pod-scale consensus-vs-bytes frontier: for
each ``MxL`` pod shape it grades flat Exp2 gossip against the two-level
hierarchical schedule (uniform intra-slice mean + Exp2 across slice
leaders) on spectral gap per cross-slice (DCN) byte.  Pure host math — no
mesh, no jit — so it runs at 32x128 (4096 chips) in milliseconds:
    python tools/gossip_bench.py --frontier --shapes 32x32,32x128 \
        --wire bf16 --out /tmp/frontier.json

``--async-frontier`` grades the straggler-immunity claim of
``async_window_gossip``: one rank throttled ``--throttle-factor`` x on
Exp2(n), wall-clock until the fleet's max consensus distance contracts to
``--target-ratio`` of its initial value, synchronous lockstep (staleness
bound 0, the straggler's sleep charged to EVERY tick via a chaos
``throttle`` fault — the PR 5 delay ledger keeps the attribution
reproducible) vs bounded-staleness async (the straggler only completes a
step every ``factor`` ticks; the fleet pays its delay only on the forced
sync-ups the staleness bound triggers):
    python tools/gossip_bench.py --async-frontier --virtual-cpu \
        --params 4096 --out /tmp/async_frontier.json
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_WIRE_WIDTH = {"f32": 4, "off": 4, "bf16": 2, "int8": 1, "fp8": 1}


def _frontier(args):
    """Consensus-vs-bytes frontier, hierarchical vs flat, per pod shape.

    Model (matches the AOT-proven lowering in tests/test_pod_scale.py):
    ranks are contiguous per slice, so a flat Exp2(n) hop of distance d
    crosses the slice boundary for d*M of the n senders when d < L and for
    every sender once d >= L.  The hierarchical schedule reduces
    intra-slice over ICI at full f32 width, then runs log2(M) machine
    permutes — every chip carries its slice's mean across the DCN hop in
    the wire dtype (the bytes/chip are constant in rank count at fixed M).
    Spectral gaps: flat via the circulant FFT fast path; hierarchical via
    gap(W_machine) — exact, because dense intra-slice averaging is the
    rank-one projector, so gap(kron(W_m, J/L)) == gap(W_m)
    (tests/test_topology.py::test_two_level_dense_intra_gap_is_machine_gap).
    """
    import numpy as np
    from bluefog_tpu import topology as tu

    wire_w = _WIRE_WIDTH[args.wire]
    payload = args.params * 4                 # full-width f32 bytes / chip
    report = {"schema": "bluefog-gossip-frontier-1", "params": args.params,
              "wire": args.wire, "shapes": []}
    for spec in args.shapes.split(","):
        m_s, l_s = spec.lower().strip().split("x")
        M, L = int(m_s), int(l_s)
        if M < 2 or L < 2:
            raise SystemExit(f"--shapes wants MxL with M,L >= 2, got {spec}")
        n = M * L

        # flat Exp2(n): log2(n) full-permutation rounds, f32 on every link
        flat_hops, flat_ici, flat_dcn = [], 0, 0
        for k in range(int(np.log2(n))):
            d = 1 << k
            crossing = n if d >= L else d * M   # senders whose hop leaves
            dcn_b = payload * crossing // n     # their slice (avg per chip)
            ici_b = payload - dcn_b
            flat_hops.append({"hop": f"+{d}", "link": "ici+dcn",
                              "ici_bytes": ici_b, "dcn_bytes": dcn_b})
            flat_ici += ici_b
            flat_dcn += dcn_b
        flat_gap = tu.spectral_gap(tu.ExponentialTwoGraph(n))

        # hierarchical: f32 ring-allreduce intra (ICI), wire-dtype Exp2(M)
        # permutes across slices (DCN) — every chip carries the slice mean
        intra_b = 2 * (L - 1) * payload // L
        hier_hops = [{"hop": "intra-mean", "link": "ici",
                      "ici_bytes": intra_b, "dcn_bytes": 0}]
        hier_ici, hier_dcn = intra_b, 0
        for k in range(int(np.log2(M))):
            dcn_b = args.params * wire_w
            hier_hops.append({"hop": f"+{1 << k}m", "link": "dcn",
                              "ici_bytes": 0, "dcn_bytes": dcn_b})
            hier_dcn += dcn_b
        hier_gap = tu.spectral_gap(tu.ExponentialTwoGraph(M))

        mib = float(2 ** 20)
        flat_row = {"topology": f"expo2({n})", "rounds": int(np.log2(n)),
                    "spectral_gap": flat_gap, "hops": flat_hops,
                    "ici_bytes_per_chip": flat_ici,
                    "dcn_bytes_per_chip": flat_dcn,
                    "gap_per_dcn_mib": flat_gap / (flat_dcn / mib)}
        hier_row = {"topology": f"dense({L}) x expo2({M})",
                    "rounds": 1 + int(np.log2(M)),
                    "spectral_gap": hier_gap, "hops": hier_hops,
                    "ici_bytes_per_chip": hier_ici,
                    "dcn_bytes_per_chip": hier_dcn,
                    "gap_per_dcn_mib": hier_gap / (hier_dcn / mib)}
        report["shapes"].append({
            "machines": M, "local": L, "ranks": n,
            "flat": flat_row, "hier": hier_row,
            "dcn_ratio": flat_dcn / hier_dcn,
            "frontier_ratio": (hier_row["gap_per_dcn_mib"]
                               / flat_row["gap_per_dcn_mib"]),
        })

    print(f"consensus-vs-bytes frontier, {args.params} f32/chip "
          f"({payload / 2**20:.1f} MiB model), DCN wire={args.wire}:")
    hdr = (f"{'shape':>9} {'schedule':>22} {'rounds':>7} {'gap':>7} "
           f"{'ICI MiB':>8} {'DCN MiB':>8} {'gap/DCN-MiB':>12}")
    print(hdr)
    for s in report["shapes"]:
        for tag in ("flat", "hier"):
            r = s[tag]
            print(f"{s['machines']}x{s['local']:<5} {r['topology']:>22} "
                  f"{r['rounds']:>7} {r['spectral_gap']:>7.3f} "
                  f"{r['ici_bytes_per_chip'] / 2**20:>8.2f} "
                  f"{r['dcn_bytes_per_chip'] / 2**20:>8.2f} "
                  f"{r['gap_per_dcn_mib']:>12.3f}")
        print(f"{'':>9} hierarchical moves {s['dcn_ratio']:.1f}x fewer DCN "
              f"bytes -> {s['frontier_ratio']:.1f}x contraction per DCN byte")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
        print(f"wrote {args.out}")
    return report


def _async_frontier(args):
    """Wall-clock-to-consensus, sync vs bounded-staleness async gossip.

    Both arms run the SAME strategy (``async_window_gossip`` on the same
    column-stochastic push schedule) so the comparison isolates the
    asynchrony: the sync arm pins staleness bound 0 (statically lockstep —
    trajectory-identical to combine-then-adapt) and pays the straggler's
    sleep on every tick through a chaos ``throttle`` fault; the async arm
    models the straggler with a pace table (its step completes — and its
    ``win_accumulate`` lands — only every ``factor``-th tick) and the fleet
    sleeps only when the staleness bound forces a sync-up.  Wall clock
    counts step dispatch + injected sleeps; the consensus probe between
    ticks is excluded (both arms pay it identically).
    """
    if args.virtual_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    if args.virtual_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax
    import bluefog_tpu as bf
    from bluefog_tpu import optimizers as bfopt
    from bluefog_tpu import topology as tu
    from bluefog_tpu.utils import chaos

    bf.init(platform="cpu" if args.virtual_cpu else None)
    n = bf.size()
    bf.set_topology(tu.ExponentialTwoGraph(n))
    sched = bfopt.push_schedule(bf.load_topology(), n)
    rank = args.throttle_rank % n
    factor = args.throttle_factor
    bound = args.staleness_bound
    opt = optax.sgd(0.0)           # pure gossip: grade mixing, not descent

    rng = np.random.RandomState(7)
    params0 = {"w": jnp.asarray(rng.randn(n, args.params).astype(np.float32))}
    batch = jnp.zeros((n, 1))

    def grad_fn(p, _):
        return jnp.zeros(()), jax.tree.map(jnp.zeros_like, p)

    def build(strat):
        # pre-shard everything onto the mesh: feeding uncommitted host
        # arrays would make the post-warmup call (whose inputs are the
        # sharded step outputs) retrace, polluting both the timing and
        # the retrace sentinel
        step = bfopt.make_train_step(grad_fn, strat, donate=False)
        shard = lambda t: jax.tree.map(bf.shard_distributed, t)
        params = shard(jax.tree.map(jnp.copy, params0))
        state = shard(bfopt.init_distributed(strat, params))
        step(params, state, batch)            # compile, untimed
        return step, params, state

    def consensus_max(p):
        return float(bf.consensus_distance(p).max())

    # unthrottled tick time on this backend -> the injected straggler delay
    step, params, state = build(
        bfopt.async_window_gossip(opt, sched, staleness_bound=0))
    initial = consensus_max(params)   # warm the probe on SHARDED params
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        params, state, _ = step(params, state, batch)
        bf.hard_sync(params)
        times.append(time.perf_counter() - t0)
    base = float(np.median(times))
    throttle_s = max((factor - 1) * base, 0.02)
    target = args.target_ratio * initial

    def run(strat, straggler_sleeps: bool):
        step, params, state = build(strat)
        wall, ticks, forced, stale_max = 0.0, 0, 0, 0
        stall_next = False
        while ticks < args.max_ticks:
            t0 = time.perf_counter()
            if straggler_sleeps and stall_next:
                # the fleet blocks on the straggler finishing its step
                # before the forced sync-up tick can run
                time.sleep(throttle_s)
                forced += 1
            params, state, _ = step(params, state, batch)
            bf.hard_sync(params)
            wall += time.perf_counter() - t0
            ticks += 1
            if straggler_sleeps:
                stall_next = bool(np.asarray(state.comm_state.force).any())
                stale_max = max(
                    stale_max, int(np.asarray(state.comm_state.depth).max()))
            if consensus_max(params) <= target:
                break
        return {"ticks": ticks, "wall_s": round(wall, 6),
                "reached_target": consensus_max(params) <= target,
                **({"forced_syncs": forced, "staleness_max": stale_max}
                   if straggler_sleeps else {})}

    # sync arm: lockstep program, chaos throttle charges the straggler's
    # delay to every tick (the whole fleet waits at the barrier)
    chaos.install(f"throttle:from=1,t={throttle_s},rank={rank}")
    try:
        sync_row = run(
            bfopt.async_window_gossip(opt, sched, staleness_bound=0),
            straggler_sleeps=False)
    finally:
        chaos.uninstall()

    # async arm: straggler completes a step every `factor` ticks (pace
    # table); the fleet sleeps only before bound-forced sync-ups
    pace = [factor if r == rank else 1 for r in range(n)]
    async_row = run(
        bfopt.async_window_gossip(opt, sched, staleness_bound=bound,
                                  pace=pace),
        straggler_sleeps=True)

    speedup = sync_row["wall_s"] / max(async_row["wall_s"], 1e-9)
    report = {
        "schema": "bluefog-gossip-async-1",
        "n": n, "topology": f"expo2({n})", "params": args.params,
        "staleness_bound": bound, "target_ratio": args.target_ratio,
        "base_tick_s": round(base, 6),
        "throttle": {"rank": rank, "factor": factor,
                     "t_s": round(throttle_s, 6)},
        "sync": sync_row, "async": async_row,
        "speedup": round(speedup, 3),
        "won": bool(async_row["wall_s"] < sync_row["wall_s"]
                    and async_row["reached_target"]
                    and sync_row["reached_target"]),
    }

    print(f"async frontier: expo2({n}), {args.params} f32/rank, rank {rank} "
          f"throttled {factor}x ({throttle_s * 1e3:.0f} ms/tick), "
          f"staleness bound {bound}, target {args.target_ratio:.2f}x initial "
          f"consensus:")
    print(f"{'arm':>8} {'ticks':>6} {'wall s':>8} {'forced':>7} "
          f"{'max stale':>10}")
    print(f"{'sync':>8} {sync_row['ticks']:>6} {sync_row['wall_s']:>8.3f} "
          f"{'-':>7} {'-':>10}")
    print(f"{'async':>8} {async_row['ticks']:>6} "
          f"{async_row['wall_s']:>8.3f} {async_row['forced_syncs']:>7} "
          f"{async_row['staleness_max']:>10}")
    print(f"async-to-consensus is {speedup:.2f}x "
          f"{'faster' if report['won'] else 'SLOWER'} than sync under a "
          f"{factor}x straggler")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
        print(f"wrote {args.out}")
    return report


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--virtual-cpu", action="store_true")
    parser.add_argument("--params", type=int, default=1 << 20,
                        help="elements per rank in the gossip buffer")
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--train-step", action="store_true",
                        help="also compare full CTA train-step time by "
                             "communicator: fused / unfused / empty / "
                             "allreduce (overlap + fusion cost on this "
                             "backend)")
    parser.add_argument("--frontier", action="store_true",
                        help="grade the hierarchical vs flat consensus-vs-"
                             "bytes frontier at pod shapes (host math only)")
    parser.add_argument("--shapes", default="32x32,32x128",
                        help="comma list of MxL pod shapes for --frontier")
    parser.add_argument("--wire", default="bf16",
                        choices=sorted(_WIRE_WIDTH),
                        help="DCN wire codec assumed for the hierarchical "
                             "schedule in --frontier")
    parser.add_argument("--out", default=None,
                        help="write the --frontier report as JSON here")
    parser.add_argument("--async-frontier", action="store_true",
                        help="grade sync vs bounded-staleness async gossip "
                             "wall-clock-to-consensus under a throttled rank")
    parser.add_argument("--throttle-rank", type=int, default=3,
                        help="rank the async frontier throttles")
    parser.add_argument("--throttle-factor", type=int, default=10,
                        help="slowdown factor of the throttled rank")
    parser.add_argument("--staleness-bound", type=int, default=4,
                        help="async staleness bound K for --async-frontier")
    parser.add_argument("--target-ratio", type=float, default=0.05,
                        help="stop when max consensus distance falls to this "
                             "fraction of its initial value")
    parser.add_argument("--max-ticks", type=int, default=400,
                        help="per-arm tick budget for --async-frontier")
    args = parser.parse_args()

    if args.frontier:
        _frontier(args)
        return
    if args.async_frontier:
        _async_frontier(args)
        return

    if args.virtual_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    import jax
    if args.virtual_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    import bluefog_tpu as bf
    from bluefog_tpu import schedule as sch
    from bluefog_tpu import topology as tu

    bf.init(platform="cpu" if args.virtual_cpu else None)
    n = bf.size()

    topologies = {
        "ring": tu.RingGraph(n),
        "expo2": tu.ExponentialTwoGraph(n),
        "mesh2d": tu.MeshGrid2DGraph(n),
        "star": tu.StarGraph(n),
        "full": tu.FullyConnectedGraph(n),
    }
    dyn = sch.compile_dynamic_schedules(
        lambda r: tu.GetDynamicOnePeerSendRecvRanks(
            tu.ExponentialTwoGraph(n), r), n)

    x = jnp.ones((n, args.params), jnp.float32)
    rows = []

    def measure(schedule):
        fn = jax.jit(jax.shard_map(
            lambda t: bf.ops.neighbor_allreduce(t[0], schedule)[None],
            mesh=bf.mesh(), in_specs=P("rank"), out_specs=P("rank")))
        out = bf.hard_sync(fn(x))
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(out)
        bf.hard_sync(out)
        return (time.perf_counter() - t0) / args.iters * 1e3

    for name, topo in topologies.items():
        s = sch.compile_topology(topo, weighted=True)
        send_deg = float(np.mean(s.out_degree))
        rows.append((name, s.num_rounds, send_deg, measure(s)))
    rows.append(("expo2-dynamic(1peer)", dyn[0].num_rounds,
                 float(np.mean(dyn[0].out_degree)), measure(dyn[0])))
    # the allreduce comparison line (Horovod-mode)
    fn = jax.jit(jax.shard_map(
        lambda t: bf.ops.allreduce(t[0])[None],
        mesh=bf.mesh(), in_specs=P("rank"), out_specs=P("rank")))
    out = bf.hard_sync(fn(x))
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = fn(out)
    bf.hard_sync(out)
    ar_ms = (time.perf_counter() - t0) / args.iters * 1e3

    print(f"{n} devices, {args.params} f32/rank "
          f"({args.params * 4 / 2**20:.1f} MiB model):")
    print(f"{'topology':>22} {'rounds':>7} {'x model sent/step':>18} {'ms/step':>9}")
    for name, rounds, deg, ms in rows:
        print(f"{name:>22} {rounds:>7} {deg:>18.2f} {ms:>9.2f}")
    print(f"{'global allreduce':>22} {'-':>7} {2 * (n - 1) / n:>18.2f} {ar_ms:>9.2f}")

    if args.train_step:
        _train_step_comparison(args, bf, n)


def _train_step_comparison(args, bf, n):
    """Full CTA train step (MLP, scan of 4) under different communicators.

    The empty-communicator row is the pure-compute floor; the gap between it
    and the gossip rows is the *visible* (non-overlapped) communication cost
    on this backend.  On TPU the async start/done scheduling hides most of it
    (tests/test_tpu_aot.py proves the schedule); the virtual CPU mesh runs
    collectives synchronously, so CPU gaps are an upper bound.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from bluefog_tpu import optimizers as bfopt
    from bluefog_tpu import topology as tu

    bf.set_topology(tu.ExponentialTwoGraph(n))
    dim, bsz, steps = 256, 32, 4

    def grad_fn(params, batch):
        x, y = batch
        def loss(p):
            h = jnp.tanh(x @ p["w1"])
            return jnp.mean((h @ p["w2"] - y) ** 2)
        return jax.value_and_grad(loss)(params)

    comms = {
        "gossip fused": bfopt.neighbor_communicator(bf.static_schedule()),
        "gossip unfused": bfopt.neighbor_communicator(
            bf.static_schedule(), fuse=False),
        "no comm (floor)": bfopt.empty_communicator(),
        "global allreduce": bfopt.allreduce_communicator(),
    }
    print(f"\nCTA train step (MLP {dim}x{dim}x2, batch {bsz}, scan {steps}) "
          f"by communicator:")
    print(f"{'communicator':>22} {'ms/step':>9}")
    for name, comm in comms.items():
        strat = bfopt.adapt_with_combine(optax.sgd(0.01), comm)
        params = bfopt.replicate({"w1": jnp.zeros((dim, dim)),
                                  "w2": jnp.zeros((dim, dim))})
        state = bfopt.init_distributed(strat, params)
        step = bfopt.make_train_step(grad_fn, strat, steps_per_call=steps)
        batch = tuple(jnp.zeros((n, steps, bsz, dim)) for _ in range(2))
        params, state, loss = step(params, state, batch)   # compile
        bf.hard_sync(loss)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            params, state, loss = step(params, state, batch)
        bf.hard_sync(loss)
        ms = (time.perf_counter() - t0) / (args.iters * steps) * 1e3
        print(f"{name:>22} {ms:>9.2f}")


if __name__ == "__main__":
    main()
