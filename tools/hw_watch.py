"""Automated TPU-tunnel watcher: catch an uptime window, run the battery.

Two rounds of manual probing caught zero tunnel uptime; this watcher turns
the problem into automation.  A lockfile-guarded loop probes the accelerator
(``import jax; jax.devices()`` in a subprocess — the axon plugin hangs on a
dead tunnel, so the child is killed at the timeout) every ``--interval``
seconds, appends every outcome to ``docs/measured/hw_watch_probes.log`` and
to the shared probe-state file ``.probe_state.json`` (which bench.py reads
to shorten its own probing after known-recent failures).  On the first
successful probe it runs the full measurement battery unattended, in order:

    bench.py                                 → docs/measured/bench_<tag>.json
    tools/chip_calibrate.py                  → chip_calibrate_<tag>.json
    tools/lm_bench.py --out …                → lm_bench[_pallas]_<tag>.json
    tools/step_sweep.py --out … --trace …    → step_sweep_<tag>.json + trace
    tools/tpu_validate.py --out …            → tpu_validate_<tag>.json  (LAST:
                                               Mosaic compiles can wedge the relay)
    tools/trace_analyze.py …                 → trace_split_<tag>.json (if present)
    tools/perf_fill.py --tag <tag>           → PERFORMANCE.md headline (if present)

then commits the artifact paths.  If the tunnel is still up on a later
probe (>= --battery-cooldown after the first battery), a SECOND, extended
battery fires under a ``<tag>x`` suffix — bigger batch, longer sequence,
wider sweep — so extra tunnel-hours buy headroom data beyond the
reference-comparable configs.  The battery list is resolved when the
probe succeeds (not at watcher start), so tools added while the watcher is
already running are picked up.  Single-client discipline: the watcher is
the ONLY process that should dial the tunnel while it runs (the axon relay
wedges under concurrent connections) — bench.py's fast-fallback path keeps
the driver's own probing short while the watcher owns the tunnel.

Run:        python tools/hw_watch.py            (foreground loop)
            nohup python tools/hw_watch.py &    (detached, all round)
Smoke test: python tools/hw_watch.py --once --stub-probe true --stub-battery
"""
from __future__ import annotations

import argparse
import datetime
import fcntl
import json
import os
import subprocess
import sys
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)
import bench as _bench  # noqa: E402 — single owner of probe + state logic

# env overrides keep test runs out of the real artifact dir / lock files
MEASURED = os.environ.get(
    "BLUEFOG_MEASURED_DIR", os.path.join(REPO, "docs", "measured"))
LOCKFILE = os.environ.get(
    "BLUEFOG_HW_WATCH_LOCK", os.path.join(REPO, ".hw_watch.lock"))
PROBE_LOG = os.path.join(MEASURED, "hw_watch_probes.log")


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


_lock_fd = None


def acquire_lock() -> bool:
    """Single-instance guard via flock: atomic, and released by the kernel
    on process death, so there is no stale-pid takeover race.  The pid is
    written into the file purely for human diagnosis."""
    global _lock_fd
    fd = os.open(LOCKFILE, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        os.close(fd)
        return False
    os.ftruncate(fd, 0)
    os.write(fd, str(os.getpid()).encode())
    _lock_fd = fd
    return True


def release_lock() -> None:
    global _lock_fd
    if _lock_fd is not None:
        try:
            os.unlink(LOCKFILE)       # before releasing: a new starter must
        except OSError:               # not lock the about-to-vanish inode
            pass
        try:
            os.close(_lock_fd)
        except OSError:
            pass
        _lock_fd = None


def log_probe(ok: bool, seconds: float, note: str = "") -> None:
    os.makedirs(MEASURED, exist_ok=True)
    with open(PROBE_LOG, "a") as f:
        f.write(f"{_utcnow()} ok={ok} dt={seconds:.1f}s{note}\n")


def _probe_env() -> dict:
    """Probe must dial the real accelerator: scrub CPU-forcing settings a
    test shell may have exported (conftest's virtual-mesh env)."""
    env = dict(os.environ)
    if "cpu" in env.get("JAX_PLATFORMS", "").lower():
        env.pop("JAX_PLATFORMS")
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" in flags:
        kept = [t for t in flags.split()
                if "host_platform_device_count" not in t]
        env["XLA_FLAGS"] = " ".join(kept)
    return env


def probe(timeout_s: float, stub: str | None) -> bool:
    """One accelerator probe in a subprocess; True iff a non-CPU device
    enumerates within the timeout.  Delegates to bench._probe so the probe
    command and kill loop live in exactly one place."""
    if stub is not None:
        return subprocess.run(["/bin/sh", "-c", stub]).returncode == 0
    return _bench._probe(_probe_env(), timeout_s)


def _battery_steps(tag: str, stage: int = 0) -> list:
    """(name, argv, timeout_s, stdout_capture_path|None, extra_env|None),
    resolved at fire time so tools added after watcher start are included.

    Stage 0 is the standard battery (reference-comparable configs + the
    PERFORMANCE.md fill).  Stage 1 — fired on a later successful probe if
    the tunnel stays up — pushes the same tools harder (bigger batch,
    longer sequence, wider sweep) under a ``<tag>x`` suffix: once the
    parity numbers are banked, extra tunnel-hours buy headroom data."""
    py = sys.executable
    m = MEASURED
    lm = os.path.join(REPO, "tools", "lm_bench.py")
    ta = os.path.join(REPO, "tools", "trace_analyze.py")
    pf = os.path.join(REPO, "tools", "perf_fill.py")
    if stage > 0:
        tag = f"{tag}x"
        steps = [
            ("bench_big", [py, os.path.join(REPO, "bench.py")], 3600,
             os.path.join(m, f"bench_{tag}.json"),
             {"BLUEFOG_BENCH_BATCH": "128", "BLUEFOG_BENCH_ITERS": "20",
              "BLUEFOG_BENCH_STEPS_PER_CALL": "10"}),
            # batch-scaling point: if 256 wins on img/s+MFU it becomes the
            # recommended headline config (ResNet-50 bf16 activations at
            # 256x224^2 fit comfortably in 16 GB HBM)
            ("bench_b256", [py, os.path.join(REPO, "bench.py")], 3600,
             os.path.join(m, f"bench_b256_{tag}.json"),
             {"BLUEFOG_BENCH_BATCH": "256", "BLUEFOG_BENCH_ITERS": "20",
              "BLUEFOG_BENCH_STEPS_PER_CALL": "10"}),
            ("step_sweep_wide",
             [py, os.path.join(REPO, "tools", "step_sweep.py"),
              "--sweep", "1,2,5,10,20", "--batch", "128",
              "--out", os.path.join(m, f"step_sweep_{tag}.json"),
              "--trace", os.path.join(m, f"trace_{tag}")], 5400, None, None),
        ]
        if os.path.exists(lm):
            # 8192 tokens is flash-only: the XLA local-attention path
            # materializes the full score tensor, which at long context
            # does not fit 16 GB of HBM.  Flash (O(block_q) VMEM) is the
            # long-context story anyway; the XLA-attention row is banked
            # at 2048 by stage 0.  --remat: long-sequence residuals would
            # not survive to the backward otherwise.
            steps.append(("lm_bench_long_pallas",
                          [py, lm, "--pallas", "--seq", "8192",
                           "--batch", "2", "--remat", "--out",
                           os.path.join(m, f"lm_bench_pallas_{tag}.json")],
                          3600, None, None))
        if os.path.exists(ta):
            steps.append(("trace_analyze",
                          [py, ta, os.path.join(m, f"trace_{tag}"),
                           "--out",
                           os.path.join(m, f"trace_split_{tag}.json")],
                          600, None, None))
        return steps
    # Ordering under SHORT windows (round 5 measured one at ~7 minutes:
    # probe ok 06:27, tunnel dead 06:34 with step_sweep wedged mid-run):
    # cheapest-per-artifact first — bench (the headline), calibrate (30 s
    # cached), the two LM rows — then the long multi-compile sweep, and
    # the Mosaic-heavy tpu_validate last (a remote Mosaic compile can
    # wedge the relay; round 5 lost a whole window to it when it ran
    # second).  The post-timeout probe in run_battery stops a dead
    # tunnel from burning the remaining steps.
    steps = [
        ("bench", [py, os.path.join(REPO, "bench.py")], 3600,
         os.path.join(m, f"bench_{tag}.json"), None),
        ("chip_calibrate",
         [py, os.path.join(REPO, "tools", "chip_calibrate.py")], 2400,
         os.path.join(m, f"chip_calibrate_{tag}.json"), None),
        # the tripwired MFU ceiling (bench._measured_peak_flops consumes
        # only trusted probes); cheap — two matmul sizes + an HBM pass
        ("roofline",
         [py, os.path.join(REPO, "tools", "roofline.py"),
          "--out", os.path.join(m, f"roofline_{tag}.json")], 2400,
         None, None),
    ]
    if os.path.exists(lm):
        # the composed grader: gossip-DP x PP x TP at the default 2x2x2
        # carving (8 chips).  batch 2 on the XLA-attention row: the
        # non-flash local attention materializes fp32 scores, marginal
        # against 16 GB HBM at the full batch.  MFU, the number we
        # publish, is batch-robust; the Pallas row runs the full config.
        steps.append(("lm_bench",
                      [py, lm, "--batch", "2", "--out",
                       os.path.join(m, f"lm_bench_{tag}.json")],
                      2400, None, None))
        steps.append(("lm_bench_pallas",
                      [py, lm, "--pallas", "--out",
                       os.path.join(m, f"lm_bench_pallas_{tag}.json")],
                      2400, None, None))
        # the routed-MoE row: the 5-axis carve (dp=2 x pp=2 x ep=2 on the
        # same 8 chips) — tokens/s + routing health (entropy, dropped
        # fraction, aux/z) banked alongside the dense rows; the AOT byte
        # split in the artifact proves expert all_to_alls stayed on ICI
        steps.append(("lm_bench_moe",
                      [py, lm, "--moe", "--dp", "2", "--pp", "2",
                       "--tp", "1", "--sp", "1", "--ep", "2",
                       "--experts", "4", "--out",
                       os.path.join(m, f"lm_bench_moe_{tag}.json")],
                      2400, None, None))
        # the dropless fast-path row: same 5-axis carve with sort-based
        # grouped dispatch + expert-choice routing — the artifact's
        # dot_flops head-to-head (dropless vs capacity-twin compiled dot
        # FLOPs) and per_step_s_capacity give the measured win on real
        # hardware, where the grouped GEMM also exercises the Pallas path
        steps.append(("lm_bench_moe_dropless",
                      [py, lm, "--moe", "--dropless", "--router",
                       "expert_choice", "--dp", "2", "--pp", "2",
                       "--tp", "1", "--sp", "1", "--ep", "2",
                       "--experts", "4", "--out",
                       os.path.join(m, f"lm_bench_moe_dropless_{tag}.json")],
                      2400, None, None))
    sb = os.path.join(REPO, "tools", "serve_bench.py")
    if os.path.exists(sb):
        # the serving grader on the same 8 chips: 2 training replicas
        # feeding 2 serving replicas (pp=2 each) — tokens/s, per-token
        # p50/p99, decode MFU vs the roofline, refresh staleness
        steps.append(("serve_bench",
                      [py, sb, "--train-dp", "2", "--serve-dp", "2",
                       "--pp", "2", "--out",
                       os.path.join(m, f"serve_bench_{tag}.json")],
                      2400, None, None))
        # the fast-path row: self-speculative decoding (3-deep draft off
        # the first pipeline stage), int8 KV pages, and shared prefix
        # pages — same carving, gated on spec bit-identity + prefix-hit
        # TTFT beating cold + int8 halving KV bytes/token
        steps.append(("serve_bench_fast",
                      [py, sb, "--train-dp", "2", "--serve-dp", "2",
                       "--pp", "2", "--spec-decode", "3@1",
                       "--kv-dtype", "int8", "--prefix-pages", "2x8",
                       "--out",
                       os.path.join(m, f"serve_bench_fast_{tag}.json")],
                      2400, None, None))
        # the flash-decode row: the paged Pallas decode kernel on the
        # serving hot path (fused int8 dequant, shared prefix pages) —
        # gated on kernel-vs-XLA token bit-identity, plus the schema-4
        # decode-MFU-at-context sweep for both kernels on real silicon
        steps.append(("serve_bench_flash",
                      [py, sb, "--train-dp", "2", "--serve-dp", "2",
                       "--pp", "2", "--decode-kernel", "pallas@8",
                       "--kv-dtype", "int8", "--prefix-pages", "2x8",
                       "--out",
                       os.path.join(m, f"serve_bench_flash_{tag}.json")],
                      2400, None, None))
        # the MoE row: ep-carved expert-parallel serving through the
        # dropless grouped-GEMM decode path, 2-deep spec draft off the
        # dense-FFN twin — gated on spec-vs-greedy token identity, the
        # dense-twin tokens/s at equal active params, and zero DCN
        # all_to_all bytes per chip (1+1 replicas x pp=2 x ep=2 = 8)
        steps.append(("serve_bench_moe",
                      [py, sb, "--train-dp", "1", "--serve-dp", "1",
                       "--pp", "2", "--serve-moe", "4x2@2:4",
                       "--spec-decode", "2@1", "--out",
                       os.path.join(m, f"serve_bench_moe_{tag}.json")],
                      2400, None, None))
        # the scale-event row: bursty flash-crowd traffic with a parked
        # reserve replica — the autoscaler must grow into the spike and
        # the schema-3 trace row demands zero failed requests + SLO
        # recovery under the bound on real hardware too.  BLUEFOG_TRACE
        # banks the per-rank span bundle next to the artifact so the
        # trace_report step below can merge it into a Chrome trace.
        steps.append(("serve_bench_trace",
                      [py, sb, "--train-dp", "2", "--serve-dp", "2",
                       "--pp", "2", "--traffic-trace", "flash-crowd",
                       "--out",
                       os.path.join(m, f"serve_bench_trace_{tag}.json")],
                      2400, None,
                      {"BLUEFOG_TRACE":
                       os.path.join(m, f"trace_serve_{tag}")}))
        # local merge of the banked span bundles: critical-path report +
        # chrome://tracing file for the serving drill above
        steps.append(("trace_report",
                      [py, os.path.join(REPO, "tools", "trace_report.py"),
                       "--dir", os.path.join(m, f"trace_serve_{tag}"),
                       "--out", os.path.join(m, f"trace_report_{tag}.json"),
                       "--chrome",
                       os.path.join(m, f"chrome_trace_{tag}.json")],
                      600, None, None))
    # the async-gossip headline: one rank throttled 10x on the real mesh,
    # async wall-clock-to-consensus vs lockstep on the same push schedule
    # (cheap: two small-strategy compiles, tens of gossip ticks)
    steps.append(("async_frontier",
                  [py, os.path.join(REPO, "tools", "gossip_bench.py"),
                   "--async-frontier",
                   "--out", os.path.join(m, f"async_frontier_{tag}.json")],
                  1200, None, None))
    ftop = os.path.join(REPO, "tools", "fleet_top.py")
    if os.path.exists(ftop):
        # the fleet-view row: train an 8-rank estate with the gossip
        # carrier armed, scrape its own /fleet endpoint, and bank the
        # frame — gated in-tool on the schema + zero-retrace/health
        # invariants (the drill grades the carrier's donation/retrace
        # contract, not accelerator perf, so it pins jax to CPU itself)
        steps.append(("fleet_view",
                      [py, ftop, "--virtual-cpu", "--once", "--json",
                       "--out", os.path.join(m, f"fleet_view_{tag}.json")],
                      600, None, None))
    pb = os.path.join(REPO, "tools", "preempt_bench.py")
    if os.path.exists(pb):
        # the preemptible-fleet grader: a mass spot reclaim replayed
        # against a virtual-CPU fleet.  The drill grades fleet semantics
        # (goodput vs the ideal fleet, float64 trajectory continuity,
        # zero-fresh-compile warm regrowth) rather than accelerator perf,
        # so it pins jax to CPU itself and never dials the tunnel —
        # cheap enough to run before the long multi-compile sweep
        steps.append(("preempt_trace",
                      [py, os.path.join(REPO, "tools", "preempt_trace.py"),
                       "--pattern", "mass", "--world", "4", "--zones", "2",
                       "--duration", "8", "--grace", "1", "--regrant", "3",
                       "--out",
                       os.path.join(m, f"preempt_trace_{tag}.json")],
                      300, None, None))
        steps.append(("preempt_bench",
                      [py, pb, "--trace",
                       os.path.join(m, f"preempt_trace_{tag}.json"),
                       "--virtual-cpu", "4", "--flight-dir",
                       os.path.join(m, f"preempt_flight_{tag}")],
                      1200, os.path.join(m, f"preempt_bench_{tag}.json"),
                      None))
    # 1,5,10 not 1,2,5,10: one fewer ResNet compile (~5 min of window)
    # and k=2 adds nothing the amortization curve needs
    steps.append(("step_sweep",
                  [py, os.path.join(REPO, "tools", "step_sweep.py"),
                   "--sweep", "1,5,10",
                   "--out", os.path.join(m, f"step_sweep_{tag}.json"),
                   "--trace", os.path.join(m, f"trace_{tag}")], 3600,
                  None, None))
    steps.append(("tpu_validate",
                  [py, os.path.join(REPO, "tools", "tpu_validate.py"),
                   "--out", os.path.join(m, f"tpu_validate_{tag}.json")],
                  3000, None, None))
    # strategy autotune with live micro-trials: each trial banks an
    # autotune_trial_*.json into docs/measured/, which upgrades future
    # (offline) autotune() calls from analytic to banked evidence for
    # this device kind + chip count
    steps.append(("autotune_sweep",
                  [py, "-m", "bluefog_tpu.autotune", "--trials", "auto",
                   "--out", os.path.join(m, f"autotune_plan_{tag}.json")],
                  2400, None, {"PYTHONPATH": REPO}))
    if os.path.exists(ta):
        steps.append(("trace_analyze",
                      [py, ta, os.path.join(m, f"trace_{tag}"),
                       "--out", os.path.join(m, f"trace_split_{tag}.json")],
                      600, None, None))
    if os.path.exists(pf):
        steps.append(("perf_fill", [py, pf, "--tag", tag], 600, None, None))
    return steps


def _rehearsal_steps(tag: str) -> list:
    """CPU-safe smoke variants of the REAL battery commands: same tools,
    same artifact plumbing, tiny shapes.  Validates the full sequencing /
    capture / trace-analysis / fill pipeline end-to-end before the
    one-shot hardware window (tpu_validate still refuses off-TPU, which
    exercises the continue-on-failure path)."""
    py = sys.executable
    m = MEASURED
    smoke_env = {"BLUEFOG_BENCH_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                 "BLUEFOG_BENCH_IMAGE_SIZE": "32",
                 "BLUEFOG_BENCH_CLASSES": "10",
                 "BLUEFOG_COMPILE_CACHE": "off"}
    # SAME ordering as _battery_steps stage 0 (bench, calibrate, the two
    # LM rows, sweep, validate, then the local analysis/fill steps): the
    # rehearsal's whole value is validating the sequencing + capture
    # pipeline the real battery will run in the one-shot hardware window
    return [
        ("bench", [py, os.path.join(REPO, "bench.py")], 900,
         os.path.join(m, f"bench_{tag}.json"), smoke_env),
        ("chip_calibrate",
         [py, os.path.join(REPO, "tools", "chip_calibrate.py"), "--smoke"],
         600, os.path.join(m, f"chip_calibrate_{tag}.json"), None),
        ("roofline",
         [py, os.path.join(REPO, "tools", "roofline.py"), "--smoke",
          "--out", os.path.join(m, f"roofline_{tag}.json")], 600,
         None, None),
        ("lm_bench",
         [py, os.path.join(REPO, "tools", "lm_bench.py"),
          "--virtual-cpu", "--smoke",
          "--out", os.path.join(m, f"lm_bench_{tag}.json")], 900, None,
         None),
        ("lm_bench_pallas",
         [py, os.path.join(REPO, "tools", "lm_bench.py"),
          "--virtual-cpu", "--smoke", "--pallas",
          "--out", os.path.join(m, f"lm_bench_pallas_{tag}.json")], 900,
         None, None),
        ("lm_bench_moe",
         [py, os.path.join(REPO, "tools", "lm_bench.py"),
          "--virtual-cpu", "--smoke", "--moe", "--dp", "2", "--pp", "2",
          "--tp", "1", "--sp", "1", "--ep", "2", "--experts", "4",
          "--out", os.path.join(m, f"lm_bench_moe_{tag}.json")], 900,
         None, None),
        ("lm_bench_moe_dropless",
         [py, os.path.join(REPO, "tools", "lm_bench.py"),
          "--virtual-cpu", "--smoke", "--moe", "--dropless",
          "--router", "expert_choice", "--dp", "2", "--pp", "2",
          "--tp", "1", "--sp", "1", "--ep", "2", "--experts", "4",
          "--out", os.path.join(m, f"lm_bench_moe_dropless_{tag}.json")],
         900, None, None),
        ("serve_bench",
         [py, os.path.join(REPO, "tools", "serve_bench.py"),
          "--virtual-cpu", "--smoke",
          "--out", os.path.join(m, f"serve_bench_{tag}.json")], 900,
         None, None),
        ("serve_bench_fast",
         [py, os.path.join(REPO, "tools", "serve_bench.py"),
          "--virtual-cpu", "--smoke", "--spec-decode", "3@1",
          "--kv-dtype", "int8", "--prefix-pages", "2x8",
          "--out", os.path.join(m, f"serve_bench_fast_{tag}.json")], 900,
         None, None),
        ("serve_bench_flash",
         [py, os.path.join(REPO, "tools", "serve_bench.py"),
          "--virtual-cpu", "--smoke", "--decode-kernel", "pallas@8",
          "--kv-dtype", "int8", "--prefix-pages", "2x8",
          "--out", os.path.join(m, f"serve_bench_flash_{tag}.json")], 900,
         None, None),
        ("serve_bench_moe",
         [py, os.path.join(REPO, "tools", "serve_bench.py"),
          "--virtual-cpu", "--smoke", "--serve-moe", "4x2@2:4",
          "--spec-decode", "2@1",
          "--out", os.path.join(m, f"serve_bench_moe_{tag}.json")], 900,
         None, None),
        ("serve_bench_trace",
         [py, os.path.join(REPO, "tools", "serve_bench.py"),
          "--virtual-cpu", "--smoke", "--traffic-trace", "flash-crowd",
          "--out", os.path.join(m, f"serve_bench_trace_{tag}.json")], 900,
         None, {"BLUEFOG_TRACE": os.path.join(m, f"trace_serve_{tag}")}),
        ("trace_report",
         [py, os.path.join(REPO, "tools", "trace_report.py"),
          "--dir", os.path.join(m, f"trace_serve_{tag}"),
          "--out", os.path.join(m, f"trace_report_{tag}.json"),
          "--chrome", os.path.join(m, f"chrome_trace_{tag}.json")], 300,
         None, {"JAX_PLATFORMS": "cpu"}),
        ("async_frontier",
         [py, os.path.join(REPO, "tools", "gossip_bench.py"),
          "--async-frontier", "--virtual-cpu", "--params", "2048",
          "--out", os.path.join(m, f"async_frontier_{tag}.json")], 600,
         None, None),
        ("fleet_view",
         [py, os.path.join(REPO, "tools", "fleet_top.py"),
          "--virtual-cpu", "--once", "--json",
          "--out", os.path.join(m, f"fleet_view_{tag}.json")], 600,
         None, None),
        ("preempt_trace",
         [py, os.path.join(REPO, "tools", "preempt_trace.py"),
          "--pattern", "mass", "--world", "4", "--zones", "2",
          "--duration", "8", "--grace", "1", "--regrant", "3",
          "--out", os.path.join(m, f"preempt_trace_{tag}.json")], 120,
         None, {"JAX_PLATFORMS": "cpu"}),
        ("preempt_bench",
         [py, os.path.join(REPO, "tools", "preempt_bench.py"),
          "--trace", os.path.join(m, f"preempt_trace_{tag}.json"),
          "--virtual-cpu", "4",
          "--flight-dir", os.path.join(m, f"preempt_flight_{tag}")], 600,
         os.path.join(m, f"preempt_bench_{tag}.json"), None),
        ("step_sweep",
         [py, os.path.join(REPO, "tools", "step_sweep.py"),
          "--sweep", "1,2", "--batch", "1", "--iters", "1", "--allow-cpu",
          "--out", os.path.join(m, f"step_sweep_{tag}.json"),
          "--trace", os.path.join(m, f"trace_{tag}")], 1200, None,
         smoke_env),
        ("tpu_validate",
         [py, os.path.join(REPO, "tools", "tpu_validate.py"),
          "--out", os.path.join(m, f"tpu_validate_{tag}.json")],
         300, None, {"JAX_PLATFORMS": "cpu"}),
        ("autotune_sweep",
         [py, "-m", "bluefog_tpu.autotune", "--virtual-cpu", "--smoke",
          "--out", os.path.join(m, f"autotune_plan_{tag}.json")], 900,
         None, {"PYTHONPATH": REPO, "BLUEFOG_COMPILE_CACHE": "off"}),
        ("trace_analyze",
         [py, os.path.join(REPO, "tools", "trace_analyze.py"),
          os.path.join(m, f"trace_{tag}"),
          "--out", os.path.join(m, f"trace_split_{tag}.json")], 300, None,
         None),
        ("perf_fill",
         [py, os.path.join(REPO, "tools", "perf_fill.py"), "--tag", tag,
          "--dry-run"], 300, None, None),
    ]


def _bench_env() -> dict:
    """The tunnel just answered a probe — bench need not re-probe slowly.
    The watcher holds the tunnel lock for the whole battery, so children
    must not try to take it themselves (flock is per-fd: a child blocking
    on the parent's lock would deadlock until its wait budget expires)."""
    env = _probe_env()
    env["BLUEFOG_BENCH_TUNNEL_LOCK"] = "0"
    env.setdefault("BLUEFOG_BENCH_PROBE_ATTEMPTS", "2")
    env.setdefault("BLUEFOG_BENCH_PROBE_TIMEOUT", "240")
    env.setdefault("BLUEFOG_BENCH_PROBE_SLEEP", "20")
    return env


def _is_cpu_payload(payload):
    """True if a captured artifact was measured on CPU, False if on an
    accelerator, None when the payload doesn't say.  bench/lm_bench emit a
    dict with ``on_accelerator``; chip_calibrate emits a LIST whose device
    row carries ``platform`` — both must be covered or the anti-clobber
    guard misses the list-shaped artifacts."""
    if isinstance(payload, dict):
        if "on_accelerator" in payload:
            return not payload["on_accelerator"]
        if "platform" in payload:
            return payload["platform"] == "cpu"
        return None
    if isinstance(payload, list):
        for row in payload:
            flag = _is_cpu_payload(row)
            if flag is not None:
                return flag
    return None


# battery steps that never dial the tunnel (they only read local
# artifacts): exempt from the wedge settle/re-probe and still run after
# a dead-tunnel abort — PERFORMANCE.md must be filled from whatever the
# tunnel-dialing steps managed to bank
LOCAL_STEPS = frozenset({"trace_analyze", "trace_report", "perf_fill"})


def run_battery(tag: str, stub: bool, no_commit: bool,
                stage: int = 0, rehearse: bool = False,
                probe_timeout: float = 150.0,
                stub_probe: str | None = None) -> dict:
    os.makedirs(MEASURED, exist_ok=True)
    logdir = os.path.join(MEASURED, "logs")
    os.makedirs(logdir, exist_ok=True)
    results = {}
    tunnel_dead = False
    if stub:
        steps = [("stub",
                  [sys.executable, "-c", "print('{\"stub\": true}')"],
                  60, os.path.join(MEASURED, f"bench_{tag}.json"), None)]
    elif rehearse:
        steps = _rehearsal_steps(tag)
    else:
        steps = _battery_steps(tag, stage)
    for name, argv, timeout_s, capture, extra_env in steps:
        if tunnel_dead and name not in LOCAL_STEPS:
            results[name] = {"rc": "skipped: tunnel unreachable",
                             "seconds": 0.0}
            print(f"hw_watch: battery step '{name}' -> {results[name]}",
                  flush=True)
            continue
        t0 = time.monotonic()
        log_path = os.path.join(logdir, f"{name}_{tag}.log")
        print(f"hw_watch: battery step '{name}' starting "
              f"(timeout {timeout_s}s, log {log_path})", flush=True)
        try:
            # start_new_session: a timed-out step is killed as a whole
            # process GROUP — bench/validate/sweep spawn their own probe
            # subprocesses, and an orphaned dialer hanging on the tunnel
            # would recreate the concurrent-dial wedge the lock prevents
            with open(log_path, "w") as logf:
                env = _bench_env()
                if extra_env:
                    env.update(extra_env)
                p = subprocess.Popen(
                    argv, env=env, cwd=REPO, text=True,
                    stdout=subprocess.PIPE, stderr=logf,
                    start_new_session=True)
                try:
                    out, _ = p.communicate(timeout=timeout_s)
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(p.pid, 9)
                    except OSError:
                        p.kill()
                    p.wait()
                    raise
            out = out or ""
            with open(log_path, "a") as logf:
                logf.write("\n--- stdout ---\n" + out)
            if capture:
                # keep only the JSON payload: a line-per-record stream
                # becomes an array, a single trailing object stays as-is
                lines = [ln for ln in out.splitlines() if ln.strip()]
                docs = []
                for ln in lines:
                    try:
                        docs.append(json.loads(ln))
                    except ValueError:
                        pass
                if docs:
                    payload = docs[-1] if len(docs) == 1 else docs
                    # never clobber a banked on-TPU artifact with a CPU
                    # fallback (tunnel died between the watcher's probe
                    # and the child's own): divert to a sidecar instead
                    if _is_cpu_payload(payload):
                        try:
                            with open(capture) as f:
                                prev = json.load(f)
                            if _is_cpu_payload(prev) is False:
                                capture += ".cpu_fallback"
                        except (OSError, ValueError):
                            pass
                    with open(capture, "w") as f:
                        json.dump(payload, f, indent=1)
            results[name] = {"rc": p.returncode,
                             "seconds": round(time.monotonic() - t0, 1)}
        except subprocess.TimeoutExpired:
            results[name] = {"rc": "timeout",
                             "seconds": round(time.monotonic() - t0, 1)}
            print(f"hw_watch: battery step '{name}' -> {results[name]}",
                  flush=True)
            # A wedged tunnel-dialing step usually means the relay is
            # jammed (or the tunnel dropped mid-battery).  Settle, then
            # re-probe before dialing again; if the tunnel stays dead,
            # skip the remaining tunnel-dialing steps (local ones — the
            # trace analysis and the PERFORMANCE.md fill — still run on
            # whatever was banked).  A timed-out LOCAL step implicates
            # only itself: no settle, no probe.
            if not (stub or rehearse) and name not in LOCAL_STEPS:
                settle = float(os.environ.get(
                    "BLUEFOG_HW_WATCH_SETTLE", "180"))
                print(f"hw_watch: settling {settle:.0f}s, then re-probing "
                      "the tunnel", flush=True)
                time.sleep(settle)
                pt0 = time.monotonic()
                alive = probe(probe_timeout, stub_probe)
                _bench.write_probe_state(
                    alive, time.monotonic() - pt0, writer="hw_watch")
                if not alive:
                    tunnel_dead = True
                    results["_battery"] = {"rc": f"aborted after {name}",
                                           "seconds": 0.0}
                    print("hw_watch: tunnel unreachable after timeout; "
                          "skipping remaining tunnel-dialing steps",
                          flush=True)
            continue
        except Exception as e:                      # noqa: BLE001
            results[name] = {"rc": f"error: {e}"[:200],
                             "seconds": round(time.monotonic() - t0, 1)}
        print(f"hw_watch: battery step '{name}' -> {results[name]}",
              flush=True)
    summary_tag = f"{tag}x" if stage > 0 else tag
    summary = {"tag": summary_tag, "stage": stage, "utc": _utcnow(),
               "steps": results}
    # surface the bench artifact's embedded telemetry block (step-time
    # percentiles, comm bytes, cache hit ratio, consensus sample) at
    # battery level, so the graded summary carries it directly
    try:
        with open(os.path.join(MEASURED,
                               f"bench_{summary_tag}.json")) as f:
            bench_doc = json.load(f)
        if isinstance(bench_doc, dict) and bench_doc.get("metrics_summary"):
            summary["metrics_summary"] = bench_doc["metrics_summary"]
    except Exception:
        pass
    with open(os.path.join(MEASURED, f"battery_{summary_tag}.json"),
              "w") as f:
        json.dump(summary, f, indent=1)
    if not no_commit:
        _commit_artifacts(tag)
    return summary


def _commit_artifacts(tag: str) -> None:
    """Commit only the artifact paths; never touches other staged work."""
    paths = ["docs/measured", "PERFORMANCE.md", "docs/PERFORMANCE.md"]
    existing = [p for p in paths if os.path.exists(os.path.join(REPO, p))]
    try:
        subprocess.run(["git", "add", "--"] + existing, cwd=REPO, check=True)
        subprocess.run(
            ["git", "commit", "-m",
             f"hw-watch: on-TPU measurement battery ({tag})", "--"] + existing,
            cwd=REPO, check=False)
    except Exception as e:                          # noqa: BLE001
        print(f"hw_watch: git commit failed: {e}", file=sys.stderr)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--interval", type=float, default=600.0,
                    help="seconds between probes (default 600)")
    ap.add_argument("--probe-timeout", type=float, default=150.0)
    ap.add_argument("--max-batteries", type=int, default=2,
                    help="total batteries to fire: the first is the "
                         "standard (reference-comparable) set, later ones "
                         "the extended '<tag>x' set; probing continues "
                         "afterwards, keeping the state file fresh")
    ap.add_argument("--battery-cooldown", type=float, default=1800.0,
                    help="seconds after a battery before the next may fire")
    ap.add_argument("--once", action="store_true",
                    help="single probe (plus battery on success) then exit")
    ap.add_argument("--tag", default=os.environ.get("BLUEFOG_ROUND", "r05"),
                    help="artifact filename tag (default r05)")
    ap.add_argument("--stub-probe", default=None, metavar="SHELL_CMD",
                    help="testing: run this shell command as the probe")
    ap.add_argument("--stub-battery", action="store_true",
                    help="testing: replace the battery with a stub step")
    ap.add_argument("--no-commit", action="store_true")
    ap.add_argument("--rehearse", action="store_true",
                    help="run the battery ONCE NOW with CPU-safe smoke "
                         "args (no probe needed): validates the full "
                         "pipeline before a hardware window; implies "
                         "--no-commit")
    args = ap.parse_args()

    if args.rehearse:
        # no tunnel dial happens here, but hold the tunnel lock anyway: a
        # rehearsal racing a REAL battery would steal host CPU from (and
        # interleave logs with) the one-shot hardware measurements
        with _bench.tunnel_client_lock(wait_s=0.0) as held:
            if not held:
                print("hw_watch: tunnel lock busy (real battery in "
                      "flight?); not rehearsing now", file=sys.stderr)
                return 4
            # suffixed tag: rehearsal artifacts never shadow real ones
            summary = run_battery(f"{args.tag}-rehearsal", stub=False,
                                  no_commit=True, rehearse=True)
        print(json.dumps(summary))
        bad = [n for n, r in summary["steps"].items()
               if r["rc"] != 0
               and not (n == "tpu_validate" and r["rc"] == 2)]
        return 0 if not bad else 1

    if not acquire_lock():
        print("hw_watch: another instance holds the lock; exiting",
              file=sys.stderr)
        return 3
    batteries = 0
    last_battery_end = None
    try:
        while True:
            t0 = time.monotonic()
            # the tunnel lock covers both the probe and any battery it
            # triggers: a driver-run bench.py holding the lock (it may be
            # mid-measurement on the chip) must never see a concurrent dial
            with _bench.tunnel_client_lock(wait_s=0.0) as held:
                if not held:
                    log_probe(False, 0.0, note=" skipped=tunnel-busy")
                    print("hw_watch: tunnel held by another client; "
                          "skipping this cycle", flush=True)
                    if args.once:
                        return 4
                    time.sleep(args.interval)
                    continue
                ok = probe(args.probe_timeout, args.stub_probe)
                dt = time.monotonic() - t0
                _bench.write_probe_state(ok, dt, writer="hw_watch")
                log_probe(ok, dt)
                print(f"hw_watch: probe ok={ok} dt={dt:.1f}s", flush=True)
                cooled = (last_battery_end is None
                          or time.monotonic() - last_battery_end
                          >= args.battery_cooldown)
                if ok and batteries < args.max_batteries and cooled:
                    stage = batteries       # 0 = standard, 1+ = extended
                    batteries += 1
                    summary = run_battery(args.tag, args.stub_battery,
                                          args.no_commit, stage=stage,
                                          probe_timeout=args.probe_timeout,
                                          stub_probe=args.stub_probe)
                    last_battery_end = time.monotonic()
                    log_probe(True, dt, note=f" battery={summary['steps']}")
            if args.once:
                return 0 if ok else 1
            time.sleep(max(0.0, args.interval - dt))
    except KeyboardInterrupt:
        return 0
    finally:
        release_lock()


if __name__ == "__main__":
    sys.exit(main())
