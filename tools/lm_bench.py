"""Transformer-LM benchmark: tokens/sec + MFU for the ring-SP/Pallas path.

The repo's beyond-reference surface (ring attention, zigzag layout, Pallas
flash kernels — SURVEY.md §5 long-context) gets its own measured number
beside the ResNet headline (bench.py).  A GPT-style ``RingTransformerLM``
trains on synthetic tokens with Adam; the measurement is the steady-state
training step, ``lax.scan``-batched ``--steps-per-call`` deep so one
host->device dispatch covers several optimizer steps (the tunnel's
dispatch latency otherwise dominates, see tools/chip_calibrate.py).

On the single axon chip the ring is degenerate (n=1) but the Pallas
flash-attention kernel compiles through Mosaic and does the real work —
that is the number the battery wants.  On a pod slice the sequence shards
across the mesh and the same script measures true ring-SP throughput.

MFU uses the standard analytic convention (PaLM appendix-B shape):
``train FLOPs/token = 6·N_params + 6·L·d_model·T`` (the attention term
halved for causal masking); XLA's cost-analysis count is reported
alongside as ``xla_call_flops``.

Run:    python tools/lm_bench.py --out docs/measured/lm_bench_r05.json
Smoke:  python tools/lm_bench.py --virtual-cpu --smoke
"""
import argparse
import importlib.util
import json
import os
import sys
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--virtual-cpu", action="store_true",
                    help="8-device virtual CPU mesh (smoke/testing)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CI (implies quick compile)")
    ap.add_argument("--seq", type=int, default=None,
                    help="global sequence length (default 4096; smoke 256)")
    ap.add_argument("--layers", type=int, default=None)
    ap.add_argument("--d-model", type=int, default=None)
    ap.add_argument("--heads", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--vocab", type=int, default=None)
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--steps-per-call", type=int, default=None)
    ap.add_argument("--sp-layout", default="zigzag",
                    choices=["contiguous", "zigzag"],
                    help="ring layout when the mesh has >1 device")
    ap.add_argument("--no-pallas", action="store_true",
                    help="pure-XLA attention instead of the flash kernel")
    ap.add_argument("--no-scan-layers", action="store_true",
                    help="unrolled layer stack (default scans ONE block "
                         "over depth: compile time O(1) in --layers, the "
                         "scarce resource in a tunnel window)")
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize blocks (nothing_saveable): only "
                         "layer inputs survive to the backward — required "
                         "for long-context configs whose per-layer "
                         "residuals would not fit HBM")
    ap.add_argument("--out", default=None, help="json artifact path")
    ap.add_argument("--allow-cpu", action="store_true")
    args = ap.parse_args()

    smoke = args.smoke or args.virtual_cpu
    seq = args.seq or (256 if smoke else 4096)
    layers = args.layers or (2 if smoke else 12)
    d_model = args.d_model or (64 if smoke else 1024)
    heads = args.heads or (2 if smoke else 16)
    batch = args.batch or (1 if smoke else 4)
    vocab = args.vocab or (64 if smoke else 32768)
    iters = args.iters or (2 if smoke else 5)
    steps_per_call = args.steps_per_call or (1 if smoke else 4)

    if args.virtual_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    if args.virtual_cpu:
        jax.config.update("jax_platforms", "cpu")

    from bluefog_tpu.utils.config import enable_compilation_cache
    enable_compilation_cache()

    dev = jax.devices()[0]
    if dev.platform == "cpu" and not (args.virtual_cpu or args.allow_cpu):
        print("refusing: no accelerator (pass --virtual-cpu or --allow-cpu)",
              file=sys.stderr)
        sys.exit(2)

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax
    from jax.sharding import PartitionSpec as P
    import bluefog_tpu as bf
    from bluefog_tpu import models

    bf.init(platform="cpu" if args.virtual_cpu else None)
    n = bf.size()
    if seq % n:
        raise SystemExit(
            f"--seq ({seq}) must be a multiple of the device count ({n})")
    local_T = seq // n
    on_tpu = jax.default_backend() == "tpu"
    use_pallas = (not args.no_pallas) and on_tpu
    layout = args.sp_layout if n > 1 else "contiguous"
    if layout == "zigzag" and local_T % 2:
        layout = "contiguous"

    lm = models.RingTransformerLM(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, max_seq_len=seq, axis="rank" if n > 1 else None,
        dtype=jnp.bfloat16, sp_mode="ring", sp_layout=layout, rope=True,
        use_pallas=use_pallas, scan_layers=not args.no_scan_layers,
        remat=args.remat)
    # init on the dense unparallel clone: the attention holds no params,
    # and running the flash kernel eagerly here would burn a Mosaic
    # compile (tunnel-minutes) on a shape-only computation
    params = lm.clone(axis=None, use_pallas=False).init(
        jax.random.key(0), jnp.zeros((1, local_T), jnp.int32))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))

    opt = optax.adamw(3e-4)
    opt_state = opt.init(params)

    def one_step(params, opt_state, tokens, targets):
        if n > 1:
            idx = lax.axis_index("rank")
            positions = (bf.ops.zigzag_positions(idx, n, local_T // 2)
                         if layout == "zigzag" else
                         idx * local_T + jnp.arange(local_T))
        else:
            positions = jnp.arange(local_T)

        def loss_fn(p):
            logits = lm.apply(p, tokens, positions=positions)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        if n > 1:
            grads = jax.tree.map(lambda g: lax.psum(g, "rank"), grads)
            loss = lax.pmean(loss, "rank")
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def k_steps(params, opt_state, tokens, targets):
        def body(carry, _):
            p, s = carry
            p, s, loss = one_step(p, s, tokens, targets)
            return (p, s), loss
        (params, opt_state), losses = lax.scan(
            body, (params, opt_state), None, length=steps_per_call)
        return params, opt_state, losses[-1]

    if n > 1:
        step = jax.jit(jax.shard_map(
            k_steps, mesh=bf.mesh(),
            in_specs=(P(), P(), P(None, "rank"), P(None, "rank")),
            out_specs=(P(), P(), P())))
    else:
        step = jax.jit(k_steps)

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, vocab, (batch, seq)), jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)

    xla_call_flops = None
    try:
        compiled = step.lower(params, opt_state, tokens, targets).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        f = float(ca.get("flops", 0.0))
        if f > 0:
            xla_call_flops = f
        step = compiled
    except Exception:
        pass                                # fall back to the jit path

    params, opt_state, loss = step(params, opt_state, tokens, targets)
    bf.hard_sync(loss)                      # compile + warm

    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
    bf.hard_sync(loss)
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    total_tokens = iters * steps_per_call * tokens_per_step
    tok_per_sec = total_tokens / dt
    # analytic train FLOPs/token (see module docstring for the convention)
    flops_per_token = 6 * n_params + 6 * layers * d_model * seq
    bench = _load_bench()
    peak = bench._peak_flops(dev.device_kind) if on_tpu else None
    mfu = (tok_per_sec * flops_per_token / (peak * n)) if peak else None

    doc = {
        "metric": "transformer_lm_tokens_per_sec",
        "value": round(tok_per_sec, 1),
        "unit": "tok/s",
        "ok": True,
        "on_accelerator": on_tpu,
        "device": dev.device_kind,
        "n_chips": n,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "config": {"seq": seq, "layers": layers, "d_model": d_model,
                   "heads": heads, "batch": batch, "vocab": vocab,
                   "n_params": n_params, "sp_layout": layout,
                   "use_pallas": use_pallas,
                   "scan_layers": not args.no_scan_layers,
                   "remat": args.remat,
                   "steps_per_call": steps_per_call, "iters": iters},
        "flops_per_token": flops_per_token,
        "xla_call_flops": xla_call_flops,
        "final_loss": float(loss),
    }
    print(json.dumps(doc))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)


if __name__ == "__main__":
    main()
