"""End-to-end grader for the decentralized LLM at production shape.

Trains the composed transformer — gossip-DP x pipeline x tensor x Ulysses
on ONE mesh (``bluefog_tpu.parallel.compose``) — through the full step
machinery (buffer donation, ``adapt_with_combine(delayed=True)`` pipelined
gossip, fused ``--steps-per-call``, chaos/flight instrumentation, retrace
sentinel) and grades it on every axis the paper's claim rides on:

* **per-step time / tokens-per-sec / MFU** against the trusted roofline
  ceiling (``bench._peak_flops``; null off-TPU);
* **overlap fraction** of the gossip permutes under compute, via a
  ``jax.profiler`` trace fed to tools/trace_analyze (null when the
  platform emits no usable device track — CPU fallback);
* **ICI-vs-DCN byte attribution** from pre-optimization StableHLO
  (``utils.hlo_bytes.stablehlo_wire_stats``): gossip permutes are the
  only cross-slice traffic and carry the wire codec; PP/TP/SP
  collectives stay intra-slice at the compute dtype;
* **DCN wire sweep**: the same carving AOT-lowered at f32 / bf16 /
  fp8@64 gossip codecs, pinning the bytes each buys;
* **invariants**: donation intact after the run, retrace sentinel 0
  after warmup;
* optional **chaos**: ``--chaos 'throttle:...'`` injects a straggler whose
  flight bundle (``--flight-dir``) tools/postmortem.py must blame
  correctly — the tier-1 test drives exactly that.

``--moe`` swaps the dense LM for the routed-MoE reference model
(``bluefog_tpu.moe``) on the full 5-axis carve (``--ep`` adds the expert
axis; ``--experts``/``--top-k``/``--capacity-factor`` size the routing,
defaulting from the ``BLUEFOG_MOE_*`` env knobs) and grades routing
health on top of the throughput rows: mean router entropy, dropped-token
fraction, load-balance aux, per-expert usage entropy — read off the
forward-only probe OUTSIDE the timed window, so the graded step stays
the production step.

``--dropless`` (with ``--moe``) swaps the padded capacity dispatch for
the sort-based grouped dropless path (``--router expert_choice`` for the
statically balanced expert-choice mode) and grades the two head-to-head
on the SAME carving: pre-opt StableHLO dot-FLOP totals for both programs
(``moe.dot_flops`` — ratio, analytic grouped-GEMM rows, the
capacity-padding fraction the delta must clear) plus the capacity twin's
per-step time (``moe.per_step_s_capacity``) when the run is live.

Emits a ``bluefog-lm-bench-2`` JSON artifact (last stdout line, and
``--out``; schema 2 adds the nullable ``moe`` block).  ``--aot-only``
skips execution and fills the byte/codec fields only — the CPU AOT
proofs (tests/test_lm_bench.py) use it to pin that cross-slice gossip
bytes follow DP-leader degree, not rank count (and, with ``--moe``,
that expert all_to_alls never cross a slice).

Run:    python tools/lm_bench.py --dp 4 --pp 2 --tp 2 --wire fp8@64 --out ...
Smoke:  python tools/lm_bench.py --virtual-cpu --smoke
MoE:    python tools/lm_bench.py --virtual-cpu --smoke --moe --ep 2 \\
            --experts 4
"""
import argparse
import dataclasses
import importlib.util
import json
import os
import sys
import tempfile
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

SCHEMA = "bluefog-lm-bench-2"


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name + "_mod", os.path.join(REPO, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--virtual-cpu", action="store_true",
                    help="virtual CPU mesh sized dp*pp*tp*sp (smoke/tests)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CI (implies quick compile)")
    ap.add_argument("--dp", type=int, default=2, help="gossip-DP replicas")
    ap.add_argument("--pp", type=int, default=2, help="pipeline stages")
    ap.add_argument("--tp", type=int, default=2, help="tensor-parallel ways")
    ap.add_argument("--sp", type=int, default=1, help="Ulysses sequence ways")
    ap.add_argument("--moe", action="store_true",
                    help="grade the routed-MoE reference LM instead of the "
                         "dense one (enables the expert axis)")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert-parallel ways (requires --moe)")
    ap.add_argument("--experts", type=int, default=None,
                    help="total experts (default BLUEFOG_MOE_EXPERTS or 8)")
    ap.add_argument("--top-k", type=int, default=None,
                    help="router top-k, 1 or 2 (default BLUEFOG_MOE_TOPK)")
    ap.add_argument("--capacity-factor", type=float, default=None,
                    help="expert capacity factor (default "
                         "BLUEFOG_MOE_CAPACITY_FACTOR or 1.25)")
    ap.add_argument("--dropless", action="store_true",
                    help="dropless grouped dispatch instead of the padded "
                         "capacity path (requires --moe); grades the two "
                         "head-to-head: per-step time + HLO dot-FLOP delta")
    ap.add_argument("--router", choices=("topk", "expert_choice"),
                    default=None,
                    help="routing mode (default BLUEFOG_MOE_ROUTER or "
                         "topk; expert_choice requires --dropless, sp=1)")
    ap.add_argument("--group-tile", type=int, default=None,
                    help="dropless grouped-GEMM tile rows (default "
                         "BLUEFOG_MOE_TILE or 8)")
    ap.add_argument("--wire", default=None,
                    help="gossip DCN codec (bf16 / fp8 / fp8@64 / int8@...)")
    ap.add_argument("--seq", type=int, default=None,
                    help="global sequence length (default 2048; smoke 32)")
    ap.add_argument("--layers", type=int, default=None)
    ap.add_argument("--d-model", type=int, default=None)
    ap.add_argument("--heads", type=int, default=None)
    ap.add_argument("--micro", type=int, default=None,
                    help="microbatches per step (pipeline fill)")
    ap.add_argument("--batch", type=int, default=None,
                    help="per-microbatch batch size")
    ap.add_argument("--vocab", type=int, default=None)
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--steps-per-call", type=int, default=None)
    ap.add_argument("--no-delayed", action="store_true",
                    help="bulk-synchronous gossip instead of the pipelined "
                         "one-step-delayed mixing (kills the overlap)")
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--pallas", action="store_true",
                    help="flash (Pallas) local attention inside ulysses "
                         "instead of the XLA reference path")
    ap.add_argument("--no-trace", action="store_true",
                    help="skip the profiler trace / overlap grading")
    ap.add_argument("--no-sweep", action="store_true",
                    help="skip the wire-codec AOT sweep")
    ap.add_argument("--aot-only", action="store_true",
                    help="lower + attribute bytes, never execute (fast "
                         "CPU proof mode)")
    ap.add_argument("--chaos", default=None,
                    help="fault spec, e.g. 'throttle:from=2,until=99,"
                         "t=0.05,rank=5'")
    ap.add_argument("--flight-dir", default=None,
                    help="dump the flight bundle here after the run")
    ap.add_argument("--out", default=None, help="json artifact path")
    ap.add_argument("--allow-cpu", action="store_true")
    args = ap.parse_args()

    if args.ep > 1 and not args.moe:
        print("refusing: --ep > 1 needs --moe (the dense LM has no expert "
              "axis)", file=sys.stderr)
        sys.exit(2)
    if (args.dropless or args.router or args.group_tile) and not args.moe:
        print("refusing: --dropless/--router/--group-tile need --moe",
              file=sys.stderr)
        sys.exit(2)
    n_chips = args.dp * args.pp * args.tp * args.sp * args.ep
    if args.virtual_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{n_chips}").strip()
    import jax
    if args.virtual_cpu:
        jax.config.update("jax_platforms", "cpu")

    from bluefog_tpu.utils.config import enable_compilation_cache
    enable_compilation_cache()

    dev = jax.devices()[0]
    on_tpu = jax.default_backend() == "tpu"
    if dev.platform == "cpu" and not (args.virtual_cpu or args.allow_cpu):
        print("refusing: no accelerator (pass --virtual-cpu or --allow-cpu)",
              file=sys.stderr)
        sys.exit(2)

    smoke = args.smoke or (args.virtual_cpu and not on_tpu)
    seq = args.seq or (32 if smoke else 2048)
    layers = args.layers or (args.pp * (1 if smoke else 2))
    d_model = args.d_model or (32 if smoke else 1024)
    heads = args.heads or (4 if smoke else 16)
    micro = args.micro or (max(2 * args.pp, 2) if smoke else 4 * args.pp)
    batch = args.batch or (2 if smoke else 4)
    vocab = args.vocab or (64 if smoke else 32768)
    iters = args.iters or (4 if smoke else 8)
    steps_per_call = args.steps_per_call or (1 if smoke else 4)

    import numpy as np
    import optax
    import bluefog_tpu as bf
    import bluefog_tpu.optimizers as bfopt
    from bluefog_tpu.parallel import compose
    from bluefog_tpu.utils import chaos as bfchaos
    from bluefog_tpu.utils import flight as bfflight
    from bluefog_tpu.utils import metrics as bfm
    from bluefog_tpu.utils.hlo_bytes import (stablehlo_dot_flops,
                                             stablehlo_wire_stats)
    from bluefog_tpu import diagnostics as bfdiag

    bf.init(platform="cpu" if args.virtual_cpu else None)
    if bf.size() != n_chips:
        raise SystemExit(
            f"carving dp*pp*tp*sp*ep = {n_chips} != device count "
            f"{bf.size()}")

    if args.moe:
        from bluefog_tpu import moe as bfmoe
        overrides = {}
        if args.experts is not None:
            overrides["num_experts"] = args.experts
        if args.top_k is not None:
            overrides["top_k"] = args.top_k
        if args.capacity_factor is not None:
            overrides["capacity_factor"] = args.capacity_factor
        if args.dropless:
            overrides["dispatch"] = "dropless"
        if args.router is not None:
            overrides["router_mode"] = args.router
        if args.group_tile is not None:
            overrides["group_tile"] = args.group_tile
        cfg = bfmoe.MoELMConfig.from_env(
            vocab=vocab, d_model=d_model, heads=heads, layers=layers,
            seq_len=seq, micro=micro, batch=batch, **overrides)
        carve_kw = {"num_experts": cfg.num_experts,
                    "capacity_factor": cfg.capacity_factor}
    else:
        cfg = compose.LMConfig(
            vocab=vocab, d_model=d_model, heads=heads, layers=layers,
            seq_len=seq, micro=micro, batch=batch)
        carve_kw = {}

    m = compose.compose_parallelism(
        args.dp, args.pp, args.tp, args.sp, args.ep, wire=args.wire,
        **carve_kw)
    cfg.validate(m)

    def build_step(mesh3d, c=None):
        c = cfg if c is None else c
        if args.moe:
            grad_fn = bfmoe.make_moe_grad_fn(c, mesh3d, remat=args.remat)
        else:
            grad_fn = compose.make_lm_grad_fn(c, mesh3d, remat=args.remat,
                                              use_pallas=args.pallas)
        return compose.make_train_step(
            mesh3d, grad_fn, optax.adam(5e-3),
            delayed=not args.no_delayed,
            steps_per_call=steps_per_call,
            reuse_batch=steps_per_call > 1,
            metrics_every_k=2, metrics_warmup=2)

    step, strategy = build_step(m)
    if args.moe:
        params = bfmoe.init_moe_params(cfg, m)
        toks = bfmoe.make_moe_batch(cfg, m)
    else:
        params = compose.init_lm_params(cfg, m)
        toks = compose.make_lm_batch(cfg, m)
    state = bfopt.init_distributed(strategy, params)
    params = compose.device_put(m, params)

    # -- AOT byte attribution (pre-opt StableHLO: states the wire dtypes
    #    honestly even where the CPU backend would constant-fold the cast)
    shlo = step.lower(params, state, toks).as_text()
    wire_bytes = stablehlo_wire_stats(shlo, m.slice_size)
    wire_bytes["slice_size"] = m.slice_size

    sweep = []
    if not args.no_sweep and m.dp > 1:
        codecs = [None, "bf16", "fp8@64"]
        if args.wire and args.wire not in codecs:
            codecs.append(args.wire)
        for w in codecs:
            mw = compose.compose_parallelism(
                args.dp, args.pp, args.tp, args.sp, args.ep, wire=w,
                **carve_kw)
            sw_step, sw_strategy = build_step(mw)
            sw_state = bfopt.init_distributed(
                sw_strategy, jax.tree.map(np.asarray, params))
            st = stablehlo_wire_stats(
                sw_step.lower(params, sw_state, toks).as_text(),
                mw.slice_size)
            sweep.append({"wire": w, "dcn_bytes": st["dcn_bytes"],
                          "dcn_dtypes": st["dcn_dtypes"],
                          "ici_bytes": st["ici_bytes"]})
        compose.compose_parallelism(       # restore the graded carving as
            args.dp, args.pp, args.tp, args.sp, args.ep,         # active
            wire=args.wire, **carve_kw)

    tokens_per_step = args.dp * micro * batch * seq
    flops_per_token = cfg.flops_per_token()
    doc = {
        "schema": SCHEMA,
        "ok": True,
        "on_accelerator": on_tpu,
        "device": dev.device_kind,
        "mesh": m.describe(),
        "config": {"seq": seq, "layers": layers, "d_model": d_model,
                   "heads": heads, "micro": micro, "batch": batch,
                   "vocab": vocab, "n_params": cfg.n_params,
                   "remat": args.remat, "pallas": args.pallas,
                   "delayed": not args.no_delayed,
                   "steps_per_call": steps_per_call, "iters": iters},
        "wire_bytes": wire_bytes,
        "wire_sweep": sweep,
        "per_step_s": None,
        "tokens_per_sec": None,
        "mfu": {"flops_per_token": flops_per_token,
                # MoE configs count ACTIVE-expert flops (top-k, not all E):
                # MoELMConfig.flops_per_token rides n_active_params
                "flops_source": "active" if args.moe else "dense",
                "model_flops_per_sec": None,
                "peak_flops_per_chip": None, "mfu": None},
        "overlap": None,
        "invariants": None,
        "losses": None,
        "loss_decreased": None,
        "chaos": args.chaos,
        "straggler": None,
        "flight_bundle": None,
        "moe": None,
    }
    if args.moe:
        doc["moe"] = {
            "num_experts": cfg.num_experts,
            "top_k": cfg.top_k,
            "ep": m.ep,
            "capacity_factor": cfg.capacity_factor,
            "capacity": cfg.capacity(m),
            "n_active_params": cfg.n_active_params,
            "dispatch": cfg.dispatch,
            "router_mode": cfg.router_mode,
            "group_tile": cfg.group_tile,
            # routing health (filled by the probe after the timed run)
            "routing_entropy": None,
            "dropped_fraction": None,
            "aux_loss": None,
            "z_loss": None,
            "usage_entropy": None,
            "ec_coverage": None,
            "dot_flops": None,
            "per_step_s_capacity": None,
        }

    if args.moe and cfg.dispatch == "dropless":
        # head-to-head vs the padded capacity path: lower the capacity/topk
        # twin of the SAME carving and count every stablehlo.dot_general.
        # Everything outside the MoE sublayer is program-identical, so the
        # delta is the dispatch scheme's matmul cost.
        from bluefog_tpu.moe.dropless import dropless_rows
        cap_cfg = dataclasses.replace(cfg, dispatch="capacity",
                                      router_mode="topk")
        cap_step, cap_strategy = build_step(m, cap_cfg)
        cap_state = bfopt.init_distributed(
            cap_strategy, jax.tree.map(np.asarray, params))
        cap_shlo = cap_step.lower(params, cap_state, toks).as_text()
        drop_flops = stablehlo_dot_flops(shlo)
        cap_flops = stablehlo_dot_flops(cap_shlo)
        # analytic grouped-GEMM rows per device per MoE sublayer: the
        # graded guarantee is row-level (HLO totals add router/attention
        # dots shared by both programs)
        e_local = cfg.num_experts // m.ep
        if cfg.router_mode == "expert_choice":
            rows_drop = e_local * m.ep * (batch // m.ep) * cfg.ec_capacity(m)
        else:
            rows_drop = dropless_rows(
                m.ep * cfg.top_k * (batch // m.ep) * (seq // m.sp),
                e_local, cfg.group_tile)
        rows_cap = cfg.num_experts * cfg.top_k * cfg.capacity(m)
        f_local = cfg.ffn_mult * d_model // m.tp
        doc["moe"]["dot_flops"] = {
            "dropless": drop_flops,
            "capacity": cap_flops,
            "delta": cap_flops - drop_flops,
            "ratio": round(drop_flops / cap_flops, 6),
            "rows_per_device": {
                "dropless": rows_drop, "capacity": rows_cap,
                "row_ratio": round(rows_drop / rows_cap, 6)},
            "padding_fraction": round(
                max(0.0, 1.0 - 1.0 / float(cfg.capacity_factor)), 6),
            # one forward grouped-FFN occurrence at the row delta: the
            # floor any honest dot-flop delta must clear
            "min_expected_delta": 4 * d_model * f_local
                                  * max(0, rows_cap - rows_drop),
        }

    if args.aot_only:
        _emit(doc, args.out)
        return

    # -- live run -----------------------------------------------------------
    if args.chaos:
        bfchaos.install(args.chaos)
    donation_probe = jax.tree.leaves(params)[0]

    losses = []

    def run(k):
        nonlocal params, state
        for _ in range(k):
            params, state, loss = step(params, state, toks)
            losses.append(float(np.asarray(loss).mean()))

    run(2)                                   # compile + warm, arms sentinel
    trace_dir = None
    if not args.no_trace:
        trace_dir = tempfile.mkdtemp(prefix="lm_bench_trace_")
        with jax.profiler.trace(trace_dir):
            t0 = time.perf_counter()
            run(iters)
            bf.hard_sync(params)
            dt = time.perf_counter() - t0
    else:
        t0 = time.perf_counter()
        run(iters)
        bf.hard_sync(params)
        dt = time.perf_counter() - t0

    per_step = dt / (iters * steps_per_call)
    tok_per_sec = tokens_per_step / per_step
    bench = _load_tool("bench")
    peak = bench._peak_flops(dev.device_kind) if on_tpu else None
    doc["per_step_s"] = round(per_step, 6)
    doc["tokens_per_sec"] = round(tok_per_sec, 1)
    doc["mfu"] = {
        "flops_per_token": flops_per_token,
        "model_flops_per_sec": round(tok_per_sec * flops_per_token, 1),
        "flops_source": "active" if args.moe else "dense",
        "peak_flops_per_chip": peak,
        "mfu": (round(tok_per_sec * flops_per_token / (peak * n_chips), 4)
                if peak else None),
    }

    if trace_dir is not None:
        try:
            spec = importlib.util.spec_from_file_location(
                "trace_analyze_mod",
                os.path.join(REPO, "tools", "trace_analyze.py"))
            ta = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(ta)
            rep = ta.analyze(ta.load_events(ta.find_trace_file(trace_dir)))
            doc["overlap"] = ({"overlap_fraction": rep["overlap_fraction"],
                               "comm_ms": rep["comm_ms"],
                               "comm_exposed_ms": rep["comm_exposed_ms"]}
                              if rep.get("ok") else None)
        except Exception as e:              # CPU traces often lack device
            doc["overlap"] = None           # tracks; the field stays null
            print(f"[lm_bench] overlap grading unavailable: {e}",
                  file=sys.stderr)

    doc["losses"] = [round(losses[0], 4), round(losses[-1], 4)]
    doc["loss_decreased"] = losses[-1] < losses[0]
    doc["invariants"] = {
        "donated": True,
        "donation_intact": bool(donation_probe.is_deleted()),
        "retraces_after_warmup":
            int(bfm.counter("bluefog_retrace_after_warmup_total").total()),
    }
    doc["ok"] = bool(doc["loss_decreased"]
                     and doc["invariants"]["donation_intact"]
                     and doc["invariants"]["retraces_after_warmup"] == 0)

    if args.moe:
        # routing health off the forward-only probe: runs OUTSIDE the timed
        # window on the final params, so the graded step stays untouched
        probe = bfmoe.make_moe_probe(cfg, m)
        health = probe(params, toks)
        doc["moe"].update({
            "routing_entropy": round(float(health["token_entropy"]), 4),
            "dropped_fraction": round(float(health["dropped_fraction"]), 4),
            "aux_loss": round(float(health["aux_loss"]), 4),
            "z_loss": round(float(health["z_loss"]), 4),
            "usage_entropy": round(float(health["usage_entropy"]), 4),
            "ec_coverage": round(float(health["ec_coverage"]), 4),
        })
        doc["ok"] = bool(doc["ok"]
                         and 0.0 <= doc["moe"]["dropped_fraction"] <= 1.0)
        if cfg.dispatch == "dropless":
            # dropless is drop-free BY CONSTRUCTION: a nonzero probe value
            # here is a dispatch bug, not a tuning problem
            doc["ok"] = bool(doc["ok"]
                             and doc["moe"]["dropped_fraction"] == 0.0)
            # time the capacity/topk twin on the same carving, outside the
            # graded window (fresh params/state; the graded step and its
            # donation probe are untouched)
            cap_cfg = dataclasses.replace(cfg, dispatch="capacity",
                                          router_mode="topk")
            cap_step, cap_strategy = build_step(m, cap_cfg)
            cap_params = compose.device_put(
                m, bfmoe.init_moe_params(cap_cfg, m))
            cap_state = bfopt.init_distributed(
                cap_strategy, jax.tree.map(np.asarray, cap_params))
            for _ in range(2):                     # compile + warm
                cap_params, cap_state, _ = cap_step(cap_params, cap_state,
                                                    toks)
            t0 = time.perf_counter()
            for _ in range(iters):
                cap_params, cap_state, _ = cap_step(cap_params, cap_state,
                                                    toks)
            bf.hard_sync(cap_params)
            doc["moe"]["per_step_s_capacity"] = round(
                (time.perf_counter() - t0) / (iters * steps_per_call), 6)

    if args.chaos:
        stragglers = bfdiag.detect_stragglers()
        table = bfdiag.last_step_times()
        doc["straggler"] = {
            "detected_ranks": [int(r) for r in stragglers],
            "step_times_s": ([round(float(t), 4) for t in table]
                             if table is not None else None),
        }
    if args.flight_dir:
        os.makedirs(args.flight_dir, exist_ok=True)
        doc["flight_bundle"] = bfflight.dump(
            os.path.join(args.flight_dir, "flight_rank0.json"),
            reason="lm_bench")

    _emit(doc, args.out)


def _emit(doc, out):
    if out:
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w") as f:
            json.dump(doc, f, indent=1)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
