"""Merge per-host BLUEFOG_METRICS JSONL logs into one job-level report.

Each host of a multi-host job writes its own ``<prefix>.metrics.jsonl``
(one registry snapshot per line, appended by ``bluefog_tpu.utils.metrics``
— see ``sample()``).  This tool is the job-level view: give it every
host's file and it merges the *last* snapshot per host —

    counters     summed across hosts (per label set)
    gauges       per-host values + max/mean (a gauge is a local fact;
                 summing step-time EWMAs would be nonsense)
    histograms   bucket-wise sum (same boundaries required — they come
                 from one code version; mismatches are reported, not
                 silently merged)

— plus time series of the operator-facing gauges (step-time EWMA,
consensus distance) across every sample of every host, so a dashboardless
operator can still see the contraction trace.

Run: python tools/metrics_report.py host0.metrics.jsonl host1.metrics.jsonl
     [--out report.json]

Output schema (stable, pinned by tests/test_metrics.py):
    {"ok": bool, "n_hosts": int, "n_samples": int, "hosts": [int, ...],
     "metrics": {name: {"type": ..., ...merged...}},
     "series": {name: [[ts, host, value], ...]},
     "summary": {...metrics-summary-shaped block...}}

Every merged histogram additionally carries a ``percentiles`` row —
p50/p90/p99 linearly interpolated from the merged cumulative buckets
(the job-level estimate; per-host reservoirs don't merge) — and the
``summary.step_time_s`` block repeats them for the operator headline.
"""
import argparse
import json
import os
import sys

# gauges worth a full time series in the report (everything else only
# contributes its final value)
SERIES_GAUGES = (
    "bluefog_step_time_ewma_s",
    "bluefog_consensus_distance_max",
    "bluefog_consensus_distance_mean",
    "bluefog_neighbor_disagreement_max",
)


def load_samples(path, notes=None):
    """All JSON lines of one host log.

    A truncated trailing line (the writer was killed mid-append) is
    expected after a crash and must not sink the report — but it must not
    vanish silently either: each undecodable line is skipped with a warning
    on stderr and, when ``notes`` is given, a note in the report.
    """
    out = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                msg = (f"warning: {path}:{lineno}: skipping torn JSONL "
                       f"line ({e.msg}); the writer likely died mid-append")
                print(msg, file=sys.stderr)
                if notes is not None:
                    notes.append(msg)
    return out


def _merge_counter(acc, doc):
    vals = acc.setdefault("values", {})
    for k, v in doc.get("values", {}).items():
        vals[k] = vals.get(k, 0.0) + v


def _merge_histogram(acc, doc, notes):
    if "buckets" not in acc:
        acc.update(count=0, sum=0.0,
                   buckets=[[b, 0] for b, _ in doc.get("buckets", [])])
    if [b for b, _ in acc["buckets"]] != [b for b, _ in doc.get("buckets", [])]:
        notes.append(f"bucket mismatch for a histogram; host skipped")
        return
    acc["count"] += doc.get("count", 0)
    acc["sum"] += doc.get("sum", 0.0)
    for slot, (_, c) in zip(acc["buckets"], doc["buckets"]):
        slot[1] += c


def _bucket_percentile(buckets, q):
    """q-th percentile (0..100) interpolated from per-bucket counts
    ``[[le, count], ...]`` with a trailing ``+Inf`` bucket.

    Linear interpolation inside the winning bucket (Prometheus
    ``histogram_quantile`` shape); an answer landing in the ``+Inf``
    bucket clamps to the last finite bound.  None when empty.
    """
    total = sum(c for _, c in buckets)
    if not total:
        return None
    target = q / 100.0 * total
    cum = 0
    lo = 0.0
    last_finite = next((b for b, _ in reversed(buckets) if b != "+Inf"), 0.0)
    for le, c in buckets:
        prev_cum, cum = cum, cum + c
        if cum >= target:
            if le == "+Inf":
                return float(last_finite)
            if c == 0:
                return float(le)
            frac = (target - prev_cum) / c
            return float(lo) + frac * (float(le) - float(lo))
        if le != "+Inf":
            lo = le
    return float(last_finite)


def _bucket_percentiles(buckets):
    return {f"p{q}": _bucket_percentile(buckets, q) for q in (50, 90, 99)}


def _merge_gauge(acc, doc, host):
    per_host = acc.setdefault("per_host", {})
    for k, v in doc.get("values", {}).items():
        per_host.setdefault(str(host), {})[k] = v


def merge(host_samples):
    """``{host: [samples...]}`` -> report dict."""
    merged = {}
    series = {}
    notes = []
    n_samples = 0
    for host, samples in sorted(host_samples.items()):
        n_samples += len(samples)
        for s in samples:
            for name in SERIES_GAUGES:
                doc = s.get("metrics", {}).get(name)
                if doc and doc.get("values"):
                    v = doc["values"].get("")
                    if v is not None:
                        series.setdefault(name, []).append(
                            [s.get("ts"), host, v])
        if not samples:
            notes.append(f"host {host}: empty log")
            continue
        last = samples[-1].get("metrics", {})
        for name, doc in last.items():
            kind = doc.get("type", "untyped")
            acc = merged.setdefault(name, {"type": kind})
            if acc["type"] != kind:
                notes.append(f"{name}: type mismatch across hosts")
                continue
            if kind == "counter":
                _merge_counter(acc, doc)
            elif kind == "histogram":
                _merge_histogram(acc, doc, notes)
            else:
                _merge_gauge(acc, doc, host)
    for name, acc in merged.items():
        if acc["type"] == "histogram" and acc.get("buckets"):
            acc["percentiles"] = _bucket_percentiles(acc["buckets"])
        if acc["type"] not in ("counter", "histogram"):
            vals = [v for per_key in acc.get("per_host", {}).values()
                    for v in per_key.values()]
            if vals:
                acc["max"] = max(vals)
                acc["mean"] = sum(vals) / len(vals)
    for name in series:
        series[name].sort(key=lambda row: (row[0] is None, row[0]))
    report = {
        "ok": True,
        "n_hosts": len(host_samples),
        "n_samples": n_samples,
        "hosts": sorted(host_samples),
        "metrics": merged,
        "series": series,
        "summary": _summary(merged),
    }
    if notes:
        report["notes"] = notes
    return report


def _summary(merged):
    """Artifact-style summary from the merged metrics (the multi-host
    counterpart of ``metrics.metrics_summary()``)."""
    def ctot(name):
        return sum(merged.get(name, {}).get("values", {}).values())

    out = {}
    h = merged.get("bluefog_step_time_s")
    if h and h.get("count"):
        out["step_time_s"] = {
            "count": h["count"],
            "mean": h["sum"] / h["count"],
            "buckets": h["buckets"],
            **_bucket_percentiles(h["buckets"]),
        }
    out["comm_bytes_total"] = ctot("bluefog_op_bytes_total")
    hits = ctot("bluefog_compile_cache_hits_total")
    misses = ctot("bluefog_compile_cache_misses_total")
    out["cache"] = {"hits": hits, "misses": misses,
                    "hit_ratio": hits / (hits + misses)
                    if hits + misses else None}
    g = merged.get("bluefog_consensus_distance_max")
    if g and "max" in g:
        out["consensus_distance_max"] = g["max"]
    out["retrace_after_warmup"] = ctot("bluefog_retrace_after_warmup_total")
    out["watchdog_stalls"] = ctot("bluefog_watchdog_stalls_total")
    return out


def window_bounds(since=None, last=None, now=None):
    """Resolve ``--since <wall-ts>`` / ``--last <secs>`` into one lower
    wall-clock bound (None = no filtering).  Both given: the later bound
    wins — the caller asked for the intersection."""
    if since is None and last is None:
        return None
    bounds = []
    if since is not None:
        bounds.append(float(since))
    if last is not None:
        if last <= 0:
            raise ValueError(f"--last must be > 0 seconds, got {last}")
        import time
        bounds.append((time.time() if now is None else float(now))
                      - float(last))
    return max(bounds)


def filter_samples(samples, cut, notes=None, label=""):
    """Keep the snapshots at or after wall time ``cut`` (samples without
    a ``ts`` are kept: better a too-wide window than silently dropped
    data, and each such keep is noted)."""
    if cut is None:
        return samples
    kept, missing = [], 0
    for s in samples:
        ts = s.get("ts")
        if ts is None:
            missing += 1
            kept.append(s)
        elif float(ts) >= cut:
            kept.append(s)
    if missing and notes is not None:
        notes.append(f"{label}: {missing} sample(s) without a ts kept "
                     "despite the --since/--last window")
    return kept


def report_from_files(paths, since=None, last=None):
    cut = window_bounds(since, last)
    host_samples = {}
    load_notes = []
    for i, path in enumerate(paths):
        samples = load_samples(path, notes=load_notes)
        samples = filter_samples(samples, cut, notes=load_notes,
                                 label=os.path.basename(path))
        # the host id rides in each line; fall back to the file position so
        # two single-host simulations on one machine still merge as two
        host = samples[-1].get("host", i) if samples else i
        if host in host_samples:
            host = max(host_samples) + 1
        host_samples[host] = samples
    report = merge(host_samples)
    if cut is not None:
        report["window"] = {"since_ts": cut}
    if load_notes:
        report.setdefault("notes", [])[:0] = load_notes
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logs", nargs="+", help="per-host *.metrics.jsonl files")
    ap.add_argument("--out", default=None)
    ap.add_argument("--since", type=float, default=None, metavar="WALL_TS",
                    help="only merge snapshots at/after this wall-clock "
                         "unix timestamp (slice a long-run log without "
                         "pre-splitting the JSONL)")
    ap.add_argument("--last", type=float, default=None, metavar="SECS",
                    help="only merge snapshots from the trailing SECS "
                         "seconds (combines with --since: later bound "
                         "wins)")
    args = ap.parse_args()
    try:
        doc = report_from_files(args.logs, since=args.since, last=args.last)
    except (OSError, ValueError) as e:
        doc = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(doc))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    sys.exit(0 if doc.get("ok") else 1)


if __name__ == "__main__":
    main()
