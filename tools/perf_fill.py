"""Fill docs/PERFORMANCE.md's measured-headline section from battery
artifacts (the final step of the hw-watch battery, tools/hw_watch.py).

Reads ``docs/measured/{bench,lm_bench,chip_calibrate,step_sweep,
trace_split,tpu_validate}_<tag>.json`` (whichever exist) and rewrites the
block between the ``HW-WATCH:BEGIN``/``HW-WATCH:END`` markers in
docs/PERFORMANCE.md — inserting the marked block after the title on first
run.  Tolerant of missing artifacts: rows only appear for data that
landed, so a partially-successful battery still publishes what it got.

Run: python tools/perf_fill.py --tag r05 [--dry-run]
"""
import argparse
import datetime
import json
import os
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
PERF = os.path.join(REPO, "docs", "PERFORMANCE.md")
MEASURED = os.environ.get(
    "BLUEFOG_MEASURED_DIR", os.path.join(REPO, "docs", "measured"))
BEGIN = "<!-- HW-WATCH:BEGIN (auto-filled by tools/perf_fill.py) -->"
END = "<!-- HW-WATCH:END -->"


def _load(name, tag):
    path = os.path.join(MEASURED, f"{name}_{tag}.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fmt_mfu(mfu):
    return f"{mfu:.1%}" if isinstance(mfu, (int, float)) else "n/a"


def render(tag):
    now = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%d")
    lines = [BEGIN,
             f"## Measured on hardware ({tag}, auto-filled {now} by the "
             "hw-watch battery)", ""]
    bench = _load("bench", tag)
    lm = _load("lm_bench", tag)
    rows = []
    if bench and bench.get("ok"):
        dev = bench.get("device", "?")
        acc = "TPU" if bench.get("on_accelerator") else "CPU FALLBACK"
        # batch/steps_per_call alongside the value: the config may adopt
        # a banked-best shape across rounds (bench._best_banked_config),
        # so the headline must say what shape produced the number.
        # Pre-r05 artifacts predate those fields — omit the suffix rather
        # than render a literal "bNone·kNone".
        batch = bench.get("batch_per_chip")
        spc = bench.get("steps_per_call")
        cfg = f", b{batch}·k{spc}" if batch is not None and spc is not None \
            else ""
        rows.append(
            f"| ResNet-50 synthetic ({acc} {dev}{cfg}) | "
            f"**{bench.get('value')} {bench.get('unit', '')}** | "
            f"MFU {_fmt_mfu(bench.get('mfu'))} | "
            f"vs V100 baseline x{bench.get('vs_baseline')} |")
    for lm_rec in (lm, _load("lm_bench_pallas", tag)):
        if lm_rec and lm_rec.get("ok"):
            cfg = lm_rec.get("config", {})
            acc = "TPU" if lm_rec.get("on_accelerator") else "CPU FALLBACK"
            rows.append(
                f"| Transformer LM ring-SP ({acc}, L{cfg.get('layers')} "
                f"d{cfg.get('d_model')} T{cfg.get('seq')}) | "
                f"**{lm_rec.get('value')} tok/s** | "
                f"MFU {_fmt_mfu(lm_rec.get('mfu'))} | "
                f"pallas={cfg.get('use_pallas')} |")
    if rows:
        lines += ["| benchmark | throughput | MFU | note |",
                  "|---|---|---|---|", *rows, ""]

    cal = _load("chip_calibrate", tag)
    if cal:
        entries = cal if isinstance(cal, list) else [cal]
        probes = [e for e in entries if isinstance(e, dict) and "probe" in e]
        if probes:
            lines += ["Chip ceilings (`tools/chip_calibrate.py`, scanned "
                      "one-dispatch loops):", ""]
            for e in probes:
                if e["probe"] == "device":
                    continue
                extra = (f"{e['tflops']} TFLOP/s" if "tflops" in e
                         else f"{e.get('gbps')} GB/s")
                flag = ""
                if e.get("suspect"):
                    flag = " — **SUSPECT, not a ceiling**"
                    if e.get("note"):
                        flag += " (see artifact note)"
                lines.append(
                    f"- `{e['probe']}`: {extra}, dispatch overhead "
                    f"{e.get('dispatch_overhead_ms', '?')} ms{flag}")
            lines.append("")

    roof = _load("roofline", tag)
    if roof and isinstance(roof, dict) and roof.get("ok"):
        probes = [p for p in roof.get("mxu", []) + roof.get("hbm", [])
                  if isinstance(p, dict) and "probe" in p]
        if probes:
            lines += ["Trusted roofline (`tools/roofline.py`, tripwired — "
                      "only `trusted` rows may become MFU denominators):",
                      ""]
            for p in probes:
                if "tflops" in p:
                    extra = f"{p['tflops']} TFLOP/s"
                elif "gbps" in p:
                    extra = (f"{p['gbps']} GB/s (dispatch-corrected "
                             f"{p.get('dispatch_corrected_gbps', '?')})")
                else:
                    extra = "no rate (tripwired before timing)"
                if p.get("suspect"):
                    flag = " — **SUSPECT, rejected**"
                elif p.get("trusted"):
                    flag = " — trusted"
                else:
                    flag = ""
                lines.append(f"- `{p['probe']}`: {extra}{flag}")
            lines.append("")

    sweep = _load("step_sweep", tag)
    if sweep and isinstance(sweep, dict) and sweep.get("rows"):
        part = (" — **PARTIAL sweep** (tunnel died before all k values ran)"
                if sweep.get("partial") else "")
        lines += [f"`steps_per_call` amortization (`tools/step_sweep.py`, "
                  f"batch {sweep.get('batch')}, best "
                  f"x{sweep.get('dispatch_amortization')}{part}):", ""]
        for p in sweep["rows"]:
            lines.append(f"- k={p['steps_per_call']}: "
                         f"{p.get('imgs_per_sec_per_chip')} img/s/chip "
                         f"(x{p.get('vs_spc1')} vs k=1, "
                         f"MFU {_fmt_mfu(p.get('mfu'))})")
        lines.append("")

    split = _load("trace_split", tag)
    if split and split.get("ok"):
        lines += [
            "Step-time decomposition (`tools/trace_analyze.py` on the "
            "step_sweep trace):", "",
            f"- device busy {split.get('busy_ms')} ms of "
            f"{split.get('wall_ms')} ms wall "
            f"(idle/dispatch {split.get('idle_ms')} ms)",
            f"- compute {split.get('compute_ms')} ms, comm "
            f"{split.get('comm_ms')} ms of which EXPOSED only "
            f"{split.get('comm_exposed_ms')} ms "
            f"(overlap fraction {split.get('overlap_fraction')})", ""]

    val = _load("tpu_validate", tag)
    if val:
        lines += [f"Kernel validation (`tools/tpu_validate.py`): "
                  f"**{val.get('summary', '?')}** over "
                  f"{val.get('n_checks', '?')} checks on "
                  f"{val.get('device', '?')}.", ""]

    if len(lines) <= 3:
        lines += ["_(battery produced no artifacts for this tag)_", ""]
    lines.append(END)
    return "\n".join(lines)


def fill(tag, dry_run=False):
    block = render(tag)
    with open(PERF) as f:
        text = f.read()
    if BEGIN in text:
        pre = text[:text.index(BEGIN)]
        if END in text:
            post = text[text.index(END) + len(END):]
        else:
            # BEGIN without END = a kill mid-write truncated the block;
            # everything after BEGIN is the partial block — drop it
            post = "\n"
        new = pre + block + post
    else:
        # first run: insert the marked block right after the title line
        head, _, rest = text.partition("\n")
        new = head + "\n\n" + block + "\n" + rest
    if not dry_run:
        with open(PERF, "w") as f:
            f.write(new)
    return new


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tag", default=os.environ.get("BLUEFOG_ROUND", "r05"))
    ap.add_argument("--dry-run", action="store_true")
    args = ap.parse_args()
    fill(args.tag, args.dry_run)
    print(json.dumps({"ok": True, "tag": args.tag,
                      "performance_md": PERF}))


if __name__ == "__main__":
    main()
