"""Merge per-rank flight-recorder bundles into a postmortem verdict.

Each rank of a failed job dumps a flight bundle (``flight_rank<r>.json``,
written by ``bluefog_tpu.utils.flight`` — on failure, on SIGTERM from the
launcher teardown, and at exit).  This tool is the cross-rank view: it
aligns the per-rank event streams by step and answers the question the
on-call person actually has — *which rank failed first, and what did the
job look like on the way down*:

    verdict        first-failed rank, failure step, failure kind/detail
                   (hard failures — exception, non-finite, watchdog
                   timeout, chaos kill — outrank launcher-inflicted
                   SIGTERMs; with no failure events at all, the rank whose
                   step counter stopped earliest is the suspect)
    step_time      per-rank mean step time + skew + straggler verdict
                   (from each bundle's step_end events when ranks dumped
                   separately; from the consensus probe's piggybacked
                   step-time samples in a single-process bundle)
    consensus      the consensus-distance trajectory leading up to the
                   failure, merged across ranks by step
    topology       the gossip edges active at dump time (post-healing),
                   from the bundles' topology blocks
    serve          (serving fleets only) merged scheduler state: dead
                   replicas, last-request ids per bundle, the request ids
                   lost with a killed replica — chaos kills name their
                   victim rank in the event, and the verdict blames that
                   rank even when another process recorded the kill
    regrow         (after a mesh-regrowth scale event) world sizes
                   before/after, coordinator rank, regrowth duration,
                   aborted attempts, and the protocol-phase timeline —
                   from the ``regrow`` bundle block + ``regrow`` events

Torn bundles (a rank killed mid-write) are skipped with a warning, never
fatal — same contract as ``tools/metrics_report.py`` with truncated JSONL.

Run: python tools/postmortem.py --dir /path/to/flight  [--out report.json]
     python tools/postmortem.py flight_rank0.json flight_rank1.json ...

Output schema (stable, pinned by tests/test_flight.py and
``make postmortem-smoke``):
    {"ok": bool, "schema": str, "n_bundles": int, "ranks": [int, ...],
     "torn": [path, ...], "verdict": {"first_failed_rank", "failure_step",
     "failure_kind", "detail"}, "per_rank": {rank: {...}},
     "step_time": {"mean_s", "skew_s", "straggler_rank"},
     "consensus": [[step, max_distance], ...], "topology": {...},
     "serve": {...} (only when a bundle carries a serve block),
     "regrow": {...} (only when a bundle saw a mesh-regrowth scale event:
     world sizes before/after, coordinator rank, duration, aborted
     attempts, and the step-ordered protocol timeline),
     "notes": [str, ...]}
"""
import argparse
import glob
import json
import os
import sys

SCHEMA = "bluefog-flight-1"

# hard failures outrank launcher-inflicted teardown signals: when rank 3
# dies and the launcher SIGTERMs the survivors, every bundle carries a
# failure-ish reason — only rank 3's is the root cause
_HARD_KINDS = ("exception", "nonfinite", "watchdog_timeout", "kill")
_SOFT_KINDS = ("sigterm",)


def load_bundle(path, notes):
    """One bundle dict, or None (with a warning note) when torn/unreadable."""
    try:
        with open(path) as f:
            return json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        msg = (f"warning: skipping torn bundle {path}: "
               f"{type(e).__name__}: {e}")
        print(msg, file=sys.stderr)
        notes.append(msg)
        return None


def _failure_candidates(rank, bundle):
    """(priority, step, ts, kind, detail, event_rank) tuples — lower sorts
    earlier; ``event_rank`` is the rank the event itself names (chaos kills
    only), which outranks the bundle's own rank for blame."""
    out = []
    for ev in bundle.get("events", ()):
        kind = ev.get("kind")
        if kind == "failure":
            name = ev.get("name", "failure")
            prio = 0 if name in _HARD_KINDS else 1
            out.append((prio, ev.get("step"), ev.get("ts"),
                        name, ev.get("detail", ""), None))
        elif kind == "chaos" and str(ev.get("name", "")).startswith("preempt"):
            # a spot reclaim is not a crash: blame the rank as "preempted"
            # with the zone/grace/victims the fault grammar recorded
            victims = ev.get("victims") or [ev.get("rank")]
            zone = ev.get("zone")
            detail = ("spot preemption (rank(s) %s%s, %s s grace)"
                      % (victims,
                         f", zone {zone}" if zone is not None else "",
                         ev.get("grace", 0)))
            blame = ev.get("rank")
            if blame is None and victims and victims[0] is not None:
                blame = victims[0]        # zone fault: blame the first victim
            out.append((0, ev.get("step"), ev.get("ts"), "preempted",
                        detail, blame))
        elif kind == "chaos" and str(ev.get("name", "")).startswith("kill"):
            # the fault grammar records WHICH rank the kill targeted; carry
            # it so the verdict can blame that rank even when the event was
            # observed from another rank's bundle (single-process sims,
            # serve fleets where the scheduler outlives the dead replica)
            out.append((0, ev.get("step"), ev.get("ts"), "kill",
                        f"chaos kill (rank {ev.get('rank')})",
                        ev.get("rank")))
    # a dump whose reason is a hard failure counts even if the failure
    # event itself was evicted from the ring
    for reason in bundle.get("reasons", ()):
        if reason in _HARD_KINDS and not any(r[0] == 0 for r in out):
            out.append((0, None, bundle.get("ts"), reason,
                        f"dump reason {reason!r}", None))
        elif reason in _SOFT_KINDS:
            out.append((1, None, bundle.get("ts"), reason,
                        f"dump reason {reason!r}", None))
    return out


def _per_rank_stats(bundle):
    last_step = None
    durs = []
    for ev in bundle.get("events", ()):
        if ev.get("kind") in ("step_begin", "step_end"):
            step = ev.get("step")
            if step is not None and (last_step is None or step > last_step):
                last_step = step
        if ev.get("kind") == "step_end" and ev.get("dur_s") is not None:
            durs.append(float(ev["dur_s"]))
    return {
        "last_step": last_step,
        "n_events": bundle.get("n_events", len(bundle.get("events", ()))),
        "dropped": bundle.get("dropped", 0),
        "reasons": list(bundle.get("reasons", ())),
        "mean_step_s": sum(durs) / len(durs) if durs else None,
    }


def _consensus_trajectory(bundles):
    """[[step, max_distance], ...] merged across ranks; probe samples
    without a step use their 1-based sample index per rank."""
    by_step = {}
    for bundle in bundles.values():
        idx = 0
        for ev in bundle.get("events", ()):
            if ev.get("kind") != "consensus":
                continue
            idx += 1
            step = ev.get("step", idx)
            val = ev.get("max")
            if val is None:
                continue
            prev = by_step.get(step)
            by_step[step] = max(prev, val) if prev is not None else val
    return [[s, by_step[s]] for s in sorted(by_step)]


def _topology_block(bundles, notes):
    candidates = []                     # (rank, ts, topology)
    for rank in sorted(bundles):
        topo = bundles[rank].get("topology")
        if not topo or "size" not in topo:
            continue
        candidates.append((rank, bundles[rank].get("ts") or 0, topo))
    if not candidates:
        notes.append("no bundle carried a topology block")
        return None
    sizes = sorted({int(t["size"]) for _, _, t in candidates})
    if len(sizes) > 1:
        # elastic membership: ranks born mid-run dump a grown world view
        notes.append(
            "bundle rank counts differ (sizes %s) — ranks joined mid-run; "
            "reporting the largest (newest) membership view"
            % ", ".join(map(str, sizes)))
    # largest world size wins, newest dump among those: the fleet's final
    # membership view
    _, _, topo = max(candidates, key=lambda c: (int(c[2]["size"]), c[1]))
    edges = []
    in_nbrs = topo.get("in_neighbors")
    if in_nbrs:
        for dst, srcs in enumerate(in_nbrs):
            edges.extend([int(src), dst] for src in srcs)
    out = {
        "size": topo.get("size"),
        "dead_ranks": topo.get("dead_ranks", []),
        "healed": topo.get("healed", False),
        "edges_at_failure": [list(e)
                             for e in sorted(map(tuple, edges))],
    }
    if "retired_ranks" in topo:
        out["retired_ranks"] = topo["retired_ranks"]
    if len(sizes) > 1:
        out["sizes_seen"] = sizes
    return out


def _step_time_block(bundles, per_rank):
    """Per-rank mean step time.  Separate-process bundles each carry their
    own step_end stream; a single-process bundle instead carries the
    probe's piggybacked per-rank step-time samples — prefer per-bundle
    means when more than one rank dumped, else fall back to the last
    consensus sample's table."""
    means = {r: s["mean_step_s"] for r, s in per_rank.items()
             if s["mean_step_s"] is not None}
    if len(means) < 2:
        for rank in sorted(bundles):
            table = None
            for ev in bundles[rank].get("events", ()):
                if ev.get("kind") == "consensus" and ev.get("step_times"):
                    table = ev["step_times"]
            if table:
                means = {r: float(t) for r, t in enumerate(table)}
                break
    if not means:
        return None
    vals = sorted(means.values())
    med = vals[len(vals) // 2]
    skew = max(vals) - min(vals)
    slowest = max(means, key=means.get)
    straggler = (slowest
                 if len(means) > 1 and means[slowest] > 2.0 * med and skew > 0
                 else None)
    return {
        "mean_s": {str(r): means[r] for r in sorted(means)},
        "skew_s": skew,
        "straggler_rank": straggler,
    }


def _serve_block(bundles, notes):
    """Merge the bundles' ``serve`` blocks (scheduler state at dump time):
    per-bundle last-request ids, dead replicas, in-flight work — and NAME
    the requests a dead replica took down (id + trace id + age), not just
    count them.  The kill/eviction event records which request ids it
    requeued; the dump-time ``in_flight_traces``/``queued`` tables carry
    those ids' trace ids and ages.  Present only when at least one bundle
    came from a serving process."""
    merged = {}
    for rank in sorted(bundles):
        sv = bundles[rank].get("serve")
        if not isinstance(sv, dict):
            continue
        if "error" in sv:
            notes.append(f"rank {rank}: serve block provider failed: "
                         f"{sv['error']}")
            continue
        merged[str(rank)] = sv
    if not merged:
        return None
    dead = sorted({d for sv in merged.values()
                   for d in sv.get("dead_replicas", ())})
    lost = sorted({r for sv in merged.values()
                   for r in sv.get("failed", ())})
    # id -> {trace, age_s, ...} from every dump-time request table
    by_id = {}
    for sv in merged.values():
        for entries in sv.get("in_flight_traces", {}).values():
            for e in entries:
                by_id[e.get("id")] = e
        for e in sv.get("queued", ()):
            by_id.setdefault(e.get("id"), e)
    # which replica's death/eviction requeued which request ids
    victims = {}
    for rank in sorted(bundles):
        for ev in bundles[rank].get("events", ()):
            if (ev.get("kind") == "serve"
                    and str(ev.get("name", "")).startswith("replica_")
                    and ev.get("requeued_requests")):
                victims.setdefault(ev.get("replica"), []).extend(
                    ev["requeued_requests"])
    lost_requests = {}
    for replica, ids in sorted(victims.items(),
                               key=lambda kv: (kv[0] is None, kv[0])):
        rows = [dict(by_id.get(i, {}), id=i) for i in sorted(set(ids))]
        lost_requests[str(replica)] = rows
        named = ", ".join(
            f"req {r['id']}" + (f" (trace {r['trace']}, "
                                f"age {r['age_s']:.3f}s)"
                                if r.get("trace") else "")
            for r in rows)
        notes.append(f"replica {replica} went down holding: {named}")
    out = {
        "per_bundle": merged,
        "dead_replicas": dead,
        "failed_request_ids": lost,
    }
    if lost_requests:
        out["lost_requests"] = lost_requests
    return out


def _regrow_block(bundles, notes):
    """Surface scale events in the verdict timeline: world sizes
    before/after, coordinator rank, regrowth duration, aborted attempts —
    from the bundles' ``regrow`` blocks plus every ``regrow``-kind event
    (begin / phase / phase_retry / abort / regrown / commit), merged and
    step-ordered.  Present only when a bundle saw a scale event."""
    merged = {}
    timeline = []
    for rank in sorted(bundles):
        rg = bundles[rank].get("regrow")
        if isinstance(rg, dict):
            if "error" in rg:
                notes.append(f"rank {rank}: regrow block provider failed: "
                             f"{rg['error']}")
            elif rg:
                merged[str(rank)] = rg
        for ev in bundles[rank].get("events", ()):
            if ev.get("kind") != "regrow":
                continue
            entry = {k: v for k, v in ev.items()
                     if k not in ("kind",) and v is not None}
            entry["bundle_rank"] = rank
            timeline.append(entry)
    if not merged and not timeline:
        return None
    timeline.sort(key=lambda e: e.get("ts") or 0)
    out = {"per_bundle": merged, "timeline": timeline}
    # headline fields from the newest per-bundle status (single-process
    # sims carry one; multi-process fleets agree on the coordinator's)
    if merged:
        newest = max(merged.values(),
                     key=lambda rg: (rg.get("committed", False),
                                     len(rg.get("phases", ()))))
        for key in ("world_before", "world_after", "coordinator",
                    "duration_s", "committed"):
            if key in newest:
                out[key] = newest[key]
        out["aborted_attempts"] = newest.get("failed_attempts", 0)
        out["aborts"] = newest.get("aborts", 0)
        if newest.get("committed"):
            notes.append(
                "world regrew %s -> %s (coordinator rank %s, %.3g s)"
                % (newest.get("world_before"), newest.get("world_after"),
                   newest.get("coordinator"),
                   newest.get("duration_s") or 0.0))
        elif newest.get("aborts"):
            notes.append(
                "a regrowth %s -> %s ABORTED and rolled back to the old "
                "world" % (newest.get("world_before"),
                           newest.get("world_after")))
    return out


def _preempt_block(bundles, notes):
    """Surface spot-preemption events in the verdict timeline: zone, grace
    window, victims, re-grant delay — from ``preempt_notice`` advance-notice
    events, ``chaos`` ``preempt:*`` faults, and warm-pool ``exec_cache``
    restores, merged and time-ordered.  Present only when a bundle saw a
    preemption."""
    timeline = []
    for rank in sorted(bundles):
        for ev in bundles[rank].get("events", ()):
            kind = ev.get("kind")
            is_preempt = (kind == "preempt_notice"
                          or (kind == "chaos" and str(
                              ev.get("name", "")).startswith("preempt"))
                          or kind == "exec_cache")
            if not is_preempt:
                continue
            entry = {k: v for k, v in ev.items() if v is not None}
            entry["bundle_rank"] = rank
            timeline.append(entry)
    if not timeline:
        return None
    timeline.sort(key=lambda e: e.get("ts") or 0)
    events = [e for e in timeline if e.get("kind") != "exec_cache"]
    victims = sorted({int(r) for e in events
                      for r in (e.get("victims") or ())})
    zones = sorted({e["zone"] for e in events if e.get("zone") is not None})
    restores = [e for e in timeline if e.get("kind") == "exec_cache"]
    out = {
        "timeline": timeline,
        "events": len([e for e in events if e.get("kind") == "chaos"]),
        "victims": victims,
        "zones": zones,
        "warm_restores": len(restores),
    }
    if victims:
        notes.append(
            "spot preemption reclaimed rank(s) %s%s — blamed as "
            "\"preempted\", not a crash" % (
                victims, f" (zone(s) {zones})" if zones else ""))
    return out


_SPARK_LEVELS = "▁▂▃▄▅▆▇█"
_LEAD_IN_POINTS = 64                 # trajectory points kept per rank/metric


def _sparkline(vals):
    """Min-max normalized unicode sparkline (the terminal 'plot')."""
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_LEVELS[0] * len(vals)
    return "".join(_SPARK_LEVELS[min(7, int((v - lo) / span * 8))]
                   for v in vals)


def _timeseries_trajectories(bundles):
    """The armed history rings each bundle embeds (flight's
    ``timeseries`` block): per metric, per rank, the tail trajectory
    leading into the dump — wall-clock points (via the bundle's
    mono/wall anchor), a sparkline, and last-vs-median so a step-time
    ramp or burn-rate spike into the verdict step reads at a glance."""
    out = {}
    for rank, bundle in sorted(bundles.items()):
        blk = bundle.get("timeseries")
        if not isinstance(blk, dict):
            continue
        anchor = blk.get("anchor") or {}
        off = float(anchor.get("wall", 0.0)) - float(anchor.get("mono", 0.0))
        for name, pts in sorted((blk.get("series") or {}).items()):
            vals = [float(v) for _, v in pts]
            if not vals:
                continue
            tail = pts[-_LEAD_IN_POINTS:]
            mid = sorted(vals)[len(vals) // 2]
            ent = {
                "n": len(vals),
                "last": vals[-1],
                "median": mid,
                "last_over_median": (vals[-1] / mid) if mid else None,
                "spark": _sparkline([float(v) for _, v in tail]),
                "points": [[round(float(t) + off, 3), float(v)]
                           for t, v in tail],
            }
            out.setdefault(name, {})[str(rank)] = ent
    return out or None


def analyze(bundles, notes=None, torn=()):
    """``{rank: bundle}`` -> postmortem report dict."""
    notes = notes if notes is not None else []
    for rank, bundle in sorted(bundles.items()):
        schema = bundle.get("schema")
        if schema != SCHEMA:
            notes.append(f"rank {rank}: unexpected schema {schema!r} "
                         f"(this tool speaks {SCHEMA})")
    per_rank = {r: _per_rank_stats(b) for r, b in bundles.items()}

    # -- verdict ----------------------------------------------------------
    candidates = []        # (priority, step, ts, rank, kind, detail)
    for rank, bundle in bundles.items():
        for prio, step, ts, kind, detail, ev_rank in _failure_candidates(
                rank, bundle):
            # a chaos kill names its victim in the event; that beats the
            # rank of whichever bundle happened to record it
            blame = ev_rank if ev_rank is not None else rank
            candidates.append((prio, step, ts, blame, kind, detail))
    verdict = {"first_failed_rank": None, "failure_step": None,
               "failure_kind": None, "detail": None}
    hard = [c for c in candidates if c[0] == 0]
    pool = hard or candidates
    if pool:
        pool.sort(key=lambda c: (
            c[0],
            c[1] if c[1] is not None else float("inf"),
            c[2] if c[2] is not None else float("inf")))
        prio, step, ts, rank, kind, detail = pool[0]
        if step is None:
            step = per_rank[rank]["last_step"] if rank in per_rank else None
        verdict = {"first_failed_rank": rank, "failure_step": step,
                   "failure_kind": kind, "detail": detail}
        if not hard:
            notes.append("no hard failure recorded; verdict is based on "
                         "teardown-signal order, which is weaker evidence")
    else:
        # no failure events anywhere: the rank whose step counter stopped
        # earliest is the stall suspect (only meaningful with a spread)
        steps = {r: s["last_step"] for r, s in per_rank.items()
                 if s["last_step"] is not None}
        if len(steps) >= 2 and max(steps.values()) > min(steps.values()):
            rank = min(steps, key=steps.get)
            verdict = {"first_failed_rank": rank,
                       "failure_step": steps[rank],
                       "failure_kind": "stalled",
                       "detail": (f"rank {rank} stopped at step "
                                  f"{steps[rank]} while others reached "
                                  f"{max(steps.values())}")}

    report = {
        "ok": True,
        "schema": SCHEMA,
        "n_bundles": len(bundles),
        "ranks": sorted(bundles),
        "torn": list(torn),
        "verdict": verdict,
        "per_rank": {str(r): per_rank[r] for r in sorted(per_rank)},
        "step_time": _step_time_block(bundles, per_rank),
        "consensus": _consensus_trajectory(bundles),
        "topology": _topology_block(bundles, notes),
    }
    trajectories = _timeseries_trajectories(bundles)
    if trajectories is not None:
        report["timeseries"] = trajectories
    serve = _serve_block(bundles, notes)
    if serve is not None:
        report["serve"] = serve
    regrow = _regrow_block(bundles, notes)
    if regrow is not None:
        report["regrow"] = regrow
    preempt = _preempt_block(bundles, notes)
    if preempt is not None:
        report["preempt"] = preempt
    if notes:
        report["notes"] = notes
    return report


def report_from_files(paths):
    notes = []
    torn = []
    bundles = {}
    for i, path in enumerate(paths):
        bundle = load_bundle(path, notes)
        if bundle is None:
            torn.append(path)
            continue
        rank = bundle.get("rank", i)
        if rank in bundles:
            notes.append(f"duplicate bundle for rank {rank} "
                         f"({path}); keeping the newest by ts")
            if bundle.get("ts", 0) <= bundles[rank].get("ts", 0):
                continue
        bundles[rank] = bundle
    if not bundles:
        return {"ok": False, "error": "no readable bundles",
                "torn": torn, "notes": notes}
    return analyze(bundles, notes, torn=torn)


def main():
    ap = argparse.ArgumentParser(
        description="Merge per-rank flight bundles into a failure verdict.")
    ap.add_argument("bundles", nargs="*", help="flight_rank*.json files")
    ap.add_argument("--dir", default=None,
                    help="directory of bundles (the launcher's --flight-dir)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    paths = list(args.bundles)
    if args.dir:
        paths += sorted(glob.glob(os.path.join(args.dir,
                                               "flight_rank*.json")))
    if not paths:
        ap.error("give bundle paths or --dir")
    doc = report_from_files(paths)
    print(json.dumps(doc))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    sys.exit(0 if doc.get("ok") else 1)


if __name__ == "__main__":
    main()
