"""Preemptible-fleet goodput bench: replay a spot trace, grade the recovery.

The ``make preempt-smoke`` centerpiece (schema ``bluefog-preempt-bench-1``):
boots a virtual-CPU gossip fleet, replays a preemption trace
(``bluefog-preempt-trace-1``, see ``tools/preempt_trace.py``) through the
real in-process machinery — the chaos ``preempt`` fault fires through
``on_train_step``, the shrink and the re-grant regrowth run the full
:func:`bluefog_tpu.resilience.regrow_world` protocol — and grades:

* **goodput fraction** — useful rank-steps achieved vs the ideal
  never-preempted fleet over the same step count (outage windows run at
  reduced width, scaled by each event's re-grant delay);
* **optimizer-progress continuity** — params are float64 and every
  preempt→regrow cycle asserts the survivors' rows cross the mesh
  boundary bit-identical (zero lost optimizer progress);
* **regrowth latency, cold vs warm** — the first cycle compiles, later
  cycles re-enter previously-seen world shapes through the warm
  executable pool (``parallel/exec_cache.py``);
* **the compile-counter invariant** — a warm-cache regrow to a
  previously-seen world shape performs ZERO fresh compiles
  (``program_cache_stats()["misses"]`` stays flat across the regrow and
  the steps after it).

Prints a one-line JSON artifact on stdout (last line) and exits non-zero
when any gate fails.  With ``--flight-dir`` the run dumps a flight bundle
whose ``preempt`` chaos events ``tools/postmortem.py`` blames as
"preempted" (zone, grace, victims) rather than "killed".

Run::

    python tools/preempt_trace.py --pattern mass --world 4 --zones 2 \
        --duration 8 --regrant 3 --out /tmp/mass.json
    python tools/preempt_bench.py --trace /tmp/mass.json --virtual-cpu 4 \
        --flight-dir /tmp/preempt_flight
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

SCHEMA = "bluefog-preempt-bench-1"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace", required=True,
                    help="bluefog-preempt-trace-1 JSON file to replay")
    ap.add_argument("--virtual-cpu", type=int, default=8,
                    help="virtual CPU device pool (must cover the world)")
    ap.add_argument("--world", type=int, default=None,
                    help="fleet size (default: the trace's world)")
    ap.add_argument("--steps-per-phase", type=int, default=2,
                    help="gossip steps between trace phases")
    ap.add_argument("--steps-per-second", type=float, default=1.0,
                    help="how many outage steps one re-grant second costs")
    ap.add_argument("--goodput-floor", type=float, default=0.5,
                    help="fail the run below this goodput fraction")
    ap.add_argument("--flight-dir", default=None,
                    help="flight bundle directory for the postmortem")
    args = ap.parse_args()

    from bluefog_tpu.run.launcher import _load_preempt_trace
    trace = _load_preempt_trace(args.trace)
    world = int(args.world or trace.get("world") or 4)
    if not trace["events"]:
        raise SystemExit(f"--trace {args.trace}: no events to replay")
    if args.virtual_cpu < world:
        raise SystemExit(f"--virtual-cpu {args.virtual_cpu} cannot host "
                         f"world {world}")

    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{args.virtual_cpu}").strip()
    if args.flight_dir:
        os.environ["BLUEFOG_FLIGHT_DIR"] = args.flight_dir
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)   # float64 trajectory oracle
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import bluefog_tpu as bf
    from bluefog_tpu import resilience as rz
    from bluefog_tpu.parallel import context as bfctx
    from bluefog_tpu.parallel import exec_cache as bfexec
    from bluefog_tpu.utils import chaos as bfchaos
    from bluefog_tpu.utils import flight as bfflight
    from bluefog_tpu.utils import metrics as bfm

    bf.init(devices=jax.devices()[:world])
    rng = np.random.default_rng(11)

    def place(arr):
        return jax.device_put(arr, NamedSharding(
            bf.get_context().mesh, P("rank")))

    params = {"w": place(rng.standard_normal((world, 16)))}
    assert params["w"].dtype == np.float64

    state = {"tick": 0, "achieved": 0, "ideal": 0}

    def run_steps(k):
        for _ in range(k):
            params["w"] = bf.neighbor_allreduce(params["w"])
            state["tick"] += 1
            state["achieved"] += bf.get_context().size
            state["ideal"] += world
        jax.block_until_ready(params["w"])

    def regrow(target):
        t0 = time.perf_counter()
        new_params, handle = rz.regrow_world(target, params)
        dt = time.perf_counter() - t0
        handle.commit()
        return new_params, dt

    def one_cycle(ev, label):
        """Preempt -> shrink -> outage -> re-grant regrowth; returns the
        per-cycle record."""
        run_steps(args.steps_per_phase)
        size_before = bf.get_context().size
        # fire the fault through the real chaos path so the flight bundle
        # carries the preempt event postmortem blames
        if ev["zone"] is not None:
            plan = (f"zones={trace['zones']};preempt:step={state['tick']+1},"
                    f"zone={ev['zone']},grace={ev['grace']},"
                    f"regrant={ev['regrant']}")
        else:
            plan = (f"preempt:step={state['tick']+1},rank={ev['victims'][0]},"
                    f"grace={ev['grace']},regrant={ev['regrant']}")
        bfchaos.install(plan)
        victims = ()
        try:
            bfchaos.on_train_step(state["tick"] + 1)
            raise SystemExit(f"preempt fault at tick {state['tick']+1} "
                             "did not fire")
        except bfchaos.RankPreempted as e:
            victims = tuple(r for r in e.ranks if r < size_before)
        finally:
            bfchaos.uninstall()
        state["tick"] += 1                    # the reclaimed step: no progress

        target = max(1, size_before - len(victims))
        pre = np.asarray(params["w"])
        m_shrink0 = bfctx.program_cache_stats()["misses"]
        new_params, shrink_s = regrow(target)
        carried = np.asarray(new_params["w"])[:target]
        shrink_lossless = bool(np.array_equal(carried, pre[:target]))
        params.update(new_params)
        shrink_compiles = (bfctx.program_cache_stats()["misses"] - m_shrink0)

        # the outage window: reduced capacity until the re-grant lands
        outage = max(1, int(round(ev["regrant"] * args.steps_per_second)))
        run_steps(outage)

        # re-grant: regrow back to the full fleet (a previously-seen shape)
        pre2 = np.asarray(params["w"])
        m0 = bfctx.program_cache_stats()["misses"]
        new_params, regrow_s = regrow(world)
        carried2 = np.asarray(new_params["w"])[:target]
        regrow_lossless = bool(np.array_equal(carried2, pre2[:target]))
        params.update(new_params)
        run_steps(args.steps_per_phase)       # steps on the regrown world
        fresh = bfctx.program_cache_stats()["misses"] - m0
        return {
            "label": label, "zone": ev["zone"], "victims": list(victims),
            "grace": ev["grace"], "regrant": ev["regrant"],
            "world_during_outage": target, "outage_steps": outage,
            "shrink_s": round(shrink_s, 6), "regrow_s": round(regrow_s, 6),
            "shrink_fresh_compiles": int(shrink_compiles),
            "regrow_fresh_compiles": int(fresh),
            "continuity_ok": bool(shrink_lossless and regrow_lossless),
        }

    cycles = [one_cycle(ev, f"event{i}")
              for i, ev in enumerate(trace["events"])]
    # always at least one warm cycle: replay the first event again so the
    # compile-counter invariant is tested even on a single-event trace
    cycles.append(one_cycle(trace["events"][0], "warm_verify"))
    run_steps(args.steps_per_phase)

    goodput = state["achieved"] / max(1, state["ideal"])
    cold = cycles[0]
    warm = cycles[1:]
    warm_fresh = max(c["regrow_fresh_compiles"] for c in warm)
    continuity = all(c["continuity_ok"] for c in cycles)
    doc = {
        "schema": SCHEMA, "ok": False, "trace": os.path.abspath(args.trace),
        "pattern": trace.get("pattern"), "world": world,
        "zones": trace["zones"], "events": len(trace["events"]),
        "steps": state["tick"],
        "achieved_rank_steps": state["achieved"],
        "ideal_rank_steps": state["ideal"],
        "goodput_fraction": round(goodput, 6),
        "goodput_floor": args.goodput_floor,
        "continuity_ok": continuity,
        "cold_regrow_s": cold["regrow_s"],
        "warm_regrow_s": round(min(c["regrow_s"] for c in warm), 6),
        "warm_fresh_compiles": int(warm_fresh),
        "preempt_events": len(cycles),
        "victims_total": sum(len(c["victims"]) for c in cycles),
        "faults_injected": int(
            bfm.counter("bluefog_faults_injected_total").total()),
        "exec_cache": bfexec.stats(),
        "cycles": cycles,
    }
    doc["ok"] = bool(continuity and warm_fresh == 0
                     and goodput >= args.goodput_floor)
    if args.flight_dir:
        doc["flight_bundle"] = bfflight.dump(reason="preempt_bench")
    print(json.dumps(doc))
    sys.exit(0 if doc["ok"] else 1)


if __name__ == "__main__":
    main()
