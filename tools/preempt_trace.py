#!/usr/bin/env python
"""Generate spot-preemption traces (schema ``bluefog-preempt-trace-1``).

The trace grammar the preemptible-fleet story replays: a JSON document of
timed preemption events, each naming its victims (an explicit rank list or
a correlated ``zone``), the advance-notice ``grace`` window, and the
``regrant`` delay before the reclaimed capacity returns.  Consumers:

* ``bfrun-tpu -np N --preempt-trace trace.json`` — the launcher SIGTERMs
  the victims at each event time, waits out the grace window while they
  drain (flight + trace bundles flush), SIGKILLs whatever remains, and
  respawns the capacity as fresh-identity joins after the re-grant delay.
* ``tools/preempt_bench.py`` — replays the trace in-process against a
  virtual fleet and grades goodput / progress continuity / regrowth
  latency.

Patterns (all seeded and deterministic):

* ``diurnal``       — reclaim waves at a regular period, rotating through
                      the zones (the evening-peak reclaim cycle).
* ``mass``          — one correlated event takes out a large fraction of
                      the zones at once (the capacity-crunch stampede).
* ``slow-regrant``  — scattered single-zone reclaims whose capacity is
                      slow to come back (regrant >> grace).

Example::

    python tools/preempt_trace.py --pattern mass --world 8 --zones 4 \
        --duration 30 --seed 0 --out /tmp/mass.json
"""
from __future__ import annotations

import argparse
import json
import random
import sys

SCHEMA = "bluefog-preempt-trace-1"


def _diurnal(args, rng) -> list:
    period = args.duration / max(1, args.events)
    events = []
    for i in range(args.events):
        events.append({
            "t": round((i + 0.5) * period, 3),
            "zone": i % args.zones,
            "grace": args.grace,
            "regrant": args.regrant,
        })
    return events


def _mass(args, rng) -> list:
    """One correlated wave: most zones reclaimed within a short burst."""
    hit = max(1, int(round(args.zones * args.fraction)))
    zones = rng.sample(range(args.zones), hit)
    t0 = args.duration * 0.4
    return [{
        "t": round(t0 + 0.05 * j, 3),     # near-simultaneous, stable order
        "zone": z,
        "grace": args.grace,
        "regrant": args.regrant,
    } for j, z in enumerate(sorted(zones))]


def _slow_regrant(args, rng) -> list:
    events = []
    for i in range(args.events):
        events.append({
            "t": round(rng.uniform(0.1, 0.9) * args.duration, 3),
            "zone": rng.randrange(args.zones),
            "grace": args.grace,
            # the defining feature: capacity stays gone for a long time
            "regrant": args.regrant * args.slow_factor,
        })
    events.sort(key=lambda e: e["t"])
    return events


PATTERNS = {"diurnal": _diurnal, "mass": _mass, "slow-regrant": _slow_regrant}


def build_trace(args) -> dict:
    rng = random.Random(args.seed)
    events = PATTERNS[args.pattern](args, rng)
    return {
        "schema": SCHEMA,
        "pattern": args.pattern,
        "seed": args.seed,
        "world": args.world,
        "zones": args.zones,
        "events": events,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--pattern", choices=sorted(PATTERNS), required=True)
    p.add_argument("--world", type=int, default=8,
                   help="fleet size the zone blocks divide (default 8)")
    p.add_argument("--zones", type=int, default=4,
                   help="correlated-failure zones (default 4)")
    p.add_argument("--duration", type=float, default=30.0,
                   help="trace horizon in seconds (default 30)")
    p.add_argument("--events", type=int, default=4,
                   help="event count for diurnal/slow-regrant (default 4)")
    p.add_argument("--fraction", type=float, default=0.5,
                   help="mass: fraction of zones reclaimed (default 0.5)")
    p.add_argument("--grace", type=float, default=2.0,
                   help="advance-notice seconds per event (default 2)")
    p.add_argument("--regrant", type=float, default=5.0,
                   help="re-grant delay seconds per event (default 5)")
    p.add_argument("--slow-factor", type=float, default=6.0,
                   help="slow-regrant: multiplier on --regrant (default 6)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=None,
                   help="output path (default: stdout)")
    args = p.parse_args(argv)
    if args.zones < 1 or args.world < args.zones:
        raise SystemExit(
            f"need 1 <= zones <= world, got zones={args.zones} "
            f"world={args.world}")
    if not (0.0 < args.fraction <= 1.0):
        raise SystemExit(f"--fraction must be in (0, 1], got {args.fraction}")
    doc = build_trace(args)
    text = json.dumps(doc, indent=2) + "\n"
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"wrote {len(doc['events'])} event(s) to {args.out}")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
