"""Subprocess mesh-regrowth drill: grow a live world by K ranks, no files.

The ``make regrow-smoke`` companion to ``tools/serve_bench.py
--traffic-trace``: boots a virtual-CPU gossip world of ``--world`` ranks,
trains it a couple of neighbor-averaging steps, then drives the full
:func:`bluefog_tpu.resilience.regrow_world` protocol to ``--target``
ranks — quiesce, coordinator handshake, host snapshot, mesh re-init,
state carry, joiner neighbor-pull — takes one step on the NEW world, and
only then commits (releasing the old world).  With ``--chaos`` the same
drill proves the abort path instead: the injected
``kill_coordinator``/``kill_joiner``/``hang_reinit`` fault must roll the
process back to the OLD world, which then demonstrates it can still
step.

Writes a flight bundle into ``--flight-dir`` (the ``regrow`` block +
event timeline ``tools/postmortem.py`` surfaces in its verdict) and
prints a one-line JSON artifact on stdout (last line)::

    {"schema": "bluefog-regrow-drill-1", "ok": true, "world_before": 4,
     "world_after": 6, "committed": true, "aborted": false, ...}

Run:   python tools/regrow_drill.py --virtual-cpu 8 --world 4 --target 6 \
           --flight-dir /tmp/regrow_flight
Abort: python tools/regrow_drill.py --virtual-cpu 8 --world 4 --target 6 \
           --chaos "kill_coordinator:step=1" --flight-dir /tmp/rg
"""
import argparse
import json
import os
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

SCHEMA = "bluefog-regrow-drill-1"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--virtual-cpu", type=int, default=8,
                    help="virtual CPU device pool (must cover --target)")
    ap.add_argument("--world", type=int, default=4,
                    help="initial world size")
    ap.add_argument("--target", type=int, default=6,
                    help="regrown world size")
    ap.add_argument("--steps", type=int, default=2,
                    help="gossip steps before the regrowth")
    ap.add_argument("--warmup-steps", type=int, default=0,
                    help="joiner entry-scale ramp ticks")
    ap.add_argument("--chaos", default=None,
                    help="fault plan (e.g. 'kill_coordinator:step=1') — "
                         "drills the abort/rollback path instead")
    ap.add_argument("--flight-dir", default=None,
                    help="flight bundle directory for the postmortem")
    args = ap.parse_args()

    if args.virtual_cpu < args.target:
        raise SystemExit(
            f"--virtual-cpu {args.virtual_cpu} cannot host "
            f"--target {args.target}")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{args.virtual_cpu}").strip()
    if args.flight_dir:
        os.environ["BLUEFOG_FLIGHT_DIR"] = args.flight_dir
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import bluefog_tpu as bf
    from bluefog_tpu import resilience as rz
    from bluefog_tpu.utils import chaos as bfchaos
    from bluefog_tpu.utils import flight as bfflight
    from bluefog_tpu.utils import metrics as bfm

    bf.init(devices=jax.devices()[:args.world])
    ctx = bf.get_context()
    rng = np.random.default_rng(7)
    w = jax.device_put(
        rng.standard_normal((args.world, 16)).astype(np.float32),
        NamedSharding(ctx.mesh, P("rank")))
    params = {"w": w}
    for s in range(args.steps):
        params = {"w": bf.neighbor_allreduce(params["w"])}
    jax.block_until_ready(params["w"])
    pre = np.asarray(params["w"])

    doc = {"schema": SCHEMA, "ok": False, "world_before": args.world,
           "world_after": None, "target": args.target,
           "committed": False, "aborted": False, "chaos": args.chaos}
    if args.chaos:
        bfchaos.install(args.chaos)
    try:
        new_params, handle = rz.regrow_world(
            args.target, params, warmup_steps=args.warmup_steps)
    except rz.RegrowAborted as e:
        doc["aborted"] = True
        doc["abort_phase"] = e.phase
        doc["abort_rank"] = e.rank
        doc["world_after"] = bf.get_context().size
        # the rollback contract: the OLD world must still step
        out = bf.neighbor_allreduce(params["w"])
        jax.block_until_ready(out)
        doc["old_world_steps_after_abort"] = True
        doc["ok"] = bool(doc["world_after"] == args.world
                         and not rz.regrow_pending())
    else:
        # survivors' rows crossed the mesh boundary losslessly
        carried = np.asarray(new_params["w"])[:min(args.world, args.target)]
        lossless = bool(np.array_equal(carried, pre[:len(carried)]))
        out = bf.neighbor_allreduce(new_params["w"])
        jax.block_until_ready(out)
        doc["committed"] = handle.commit()
        doc["world_after"] = bf.get_context().size
        doc["coordinator"] = handle.coordinator
        doc["joiners"] = list(handle.joiners)
        doc["duration_s"] = round(handle.duration_s, 6)
        doc["carry_lossless"] = lossless
        doc["retraces_after_warmup"] = int(
            bfm.counter("bluefog_retrace_after_warmup_total").total())
        doc["ok"] = bool(doc["world_after"] == args.target
                         and doc["committed"] and lossless
                         and not rz.regrow_pending())
    finally:
        if args.chaos:
            bfchaos.uninstall()
    if args.flight_dir:
        doc["flight_bundle"] = bfflight.dump(reason="regrow_drill")
    print(json.dumps(doc))
    sys.exit(0 if doc["ok"] else 1)


if __name__ == "__main__":
    main()
