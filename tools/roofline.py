"""Trusted roofline: measured MXU FLOP/s and HBM GB/s with tripwires.

The MFU denominator problem: a spec-sheet peak is a number the step never
sees, and a NAIVE measured peak is worse — round 5 banked a "641 TF/s"
matmul on a 197 TF/s chip because XLA's algebraic simplifier rewrote the
splat-operand matmul into an O(n^2) column reduction that never touched
the MXU (docs/PERFORMANCE.md, r05 retraction).  A ceiling is only usable
as a denominator if the measurement DEMONSTRABLY exercised the unit it
claims to measure.

This tool produces that ceiling.  Every MXU probe must pass three
tripwires before it is marked ``trusted``:

  1. structural — the optimized HLO of the timed program must contain a
     real dot/GEMM op (``assert_real_dot``): if the simplifier folded the
     operand away, the probe is rejected BEFORE it is timed;
  2. rate bound — the achieved FLOP/s must not exceed the spec peak
     (``check_rate_bound``): above-spec throughput always means a broken
     measurement (folded body or a sync barrier that returned at
     dispatch), never an overachieving chip;
  3. scaling — with two sizes, time(2n)/time(n) must look O(n^3)
     (~8x, threshold 4x): folding flattens the curve even when the
     absolute rate sneaks under the peak.

Only ``trusted`` (and never ``suspect``) probes are consumed by
bench.py's ``_measured_peak_flops`` as the MFU ceiling — a folded-dot
artifact can be BANKED (for the record) but can never become a
denominator.

The HBM probe is chunked and dispatch-corrected: per-call time for a
large read+write body, minus the measured per-call dispatch overhead of
an 8-element body, alongside the one-dispatch ``lax.scan`` gold number
(round 2 charged ~ms of tunnel dispatch latency to every 1 GiB copy and
published 307 GB/s on an 819 GB/s part).

Operands are random ROW-STOCHASTIC matrices (rows sum to 1): the scan
carry stays O(1) across chained matmuls, and unlike a ``jnp.full(1/n)``
splat there is no broadcast-of-scalar for the simplifier to rewrite.

Run:  python tools/roofline.py [--out PATH]     (single client on tunnel)
      python tools/roofline.py --smoke          (tiny shapes, any backend)
Prints ONE JSON document; ``--out`` also writes it atomically.
Exit code is non-zero when a non-smoke run yields NO trusted MXU probe —
a battery must notice that its ceiling measurement failed.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

# markers that the timed program really multiplies matrices: plain HLO dot,
# or the backend GEMM custom-calls it may lower to (cuBLAS/oneDNN/Mosaic)
DOT_MARKERS = (" dot(", " dot.", "= dot(", "custom_call_target=\"__onednn",
               "custom_call_target=\"__cublas", "cublas$gemm", "$gemm",
               "tpu_custom_call", "dot_general")


class RooflineError(RuntimeError):
    """A roofline tripwire fired: the measurement cannot be trusted."""


def assert_real_dot(hlo_text: str) -> None:
    """Structural tripwire: the optimized HLO must still contain a dot.

    Raises :class:`RooflineError` when no dot/GEMM marker survives
    compilation — i.e. XLA folded the operand (splat rewrite, constant
    propagation) and the timed program would measure something other
    than the MXU."""
    if not isinstance(hlo_text, str) or not hlo_text:
        raise RooflineError("empty HLO: nothing was compiled")
    low = hlo_text.lower()
    if not any(m.lower() in low for m in DOT_MARKERS):
        raise RooflineError(
            "no dot/GEMM op in the optimized HLO: XLA folded the matmul "
            "(splat operand or constant propagation) — the probe would "
            "time a reduction, not the MXU")


def check_rate_bound(flops_per_sec: float, peak_flops) -> None:
    """Rate tripwire: measured FLOP/s above the spec peak is impossible.

    Raises :class:`RooflineError` when ``flops_per_sec`` exceeds
    ``peak_flops`` (None disables the check — unknown device kind)."""
    if flops_per_sec <= 0:
        raise RooflineError(f"non-positive FLOP rate {flops_per_sec!r}")
    if peak_flops and flops_per_sec > peak_flops:
        raise RooflineError(
            f"{flops_per_sec / 1e12:.1f} TF/s exceeds the "
            f"{peak_flops / 1e12:.0f} TF/s spec peak: the operand was "
            "folded or the sync barrier returned at dispatch")


def _bench_mod():
    import bench
    return bench


def _row_stochastic(n: int, seed: int = 0):
    """Random row-stochastic [n, n] bf16 operand (rows sum to 1)."""
    import jax
    import jax.numpy as jnp
    a = jax.random.uniform(jax.random.key(seed or n), (n, n), jnp.float32,
                           0.5, 1.5)
    return (a / a.sum(axis=1, keepdims=True)).astype(jnp.bfloat16)


def _scan_fn(body, iters):
    import jax
    from jax import lax
    return jax.jit(lambda x0: lax.scan(
        lambda c, _: (body(c), None), x0, None, length=iters)[0])


def _timed(hard_sync, f, x):
    t0 = time.perf_counter()
    hard_sync(f(x))
    return time.perf_counter() - t0


def _dispatch_overhead_s(hard_sync, iters: int) -> float:
    """Per-call host->device dispatch overhead, from an 8-element body
    whose device time is negligible next to the launch cost."""
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda y: y * 1.0001)
    y = hard_sync(f(jnp.ones((8,), jnp.float32)))
    t0 = time.perf_counter()
    for _ in range(iters):
        y = f(y)
    hard_sync(y)
    return (time.perf_counter() - t0) / iters


def mxu_probe(n: int, iters: int, hard_sync, spec_peak) -> dict:
    """One tripwired MXU calibration at size ``n``.

    Returns a row with ``flops_per_sec`` and ``trusted``/``suspect``
    flags; tripwire failures are recorded in the row (``suspect`` +
    ``note``) rather than raised, so one bad size cannot abort the
    battery step."""
    a = _row_stochastic(n)
    f = _scan_fn(lambda c: a @ c, iters)
    row = {"probe": f"mxu_bf16_{n}", "n": n, "iters": iters,
           "trusted": False, "suspect": False,
           "spec_peak_tflops": round(spec_peak / 1e12, 1)
           if spec_peak else None}
    try:
        compiled = f.lower(a).compile()
        assert_real_dot(compiled.as_text())
    except RooflineError as e:
        row.update(suspect=True, note=f"structural tripwire: {e}")
        return row
    hard_sync(compiled(a))                        # warm
    per_iter = _timed(hard_sync, compiled, a) / iters
    flops = 2.0 * n ** 3 / per_iter
    row.update(ms=round(per_iter * 1e3, 3),
               flops_per_sec=flops, tflops=round(flops / 1e12, 1))
    try:
        check_rate_bound(flops, spec_peak)
    except RooflineError as e:
        row.update(suspect=True, note=f"rate tripwire: {e}")
        return row
    if spec_peak is None:
        row["note"] = ("unknown device kind: above-peak check skipped, "
                       "trust rests on the structural tripwire alone")
    row["trusted"] = True
    return row


def apply_scaling_tripwire(rows: list) -> None:
    """Cross-size O(n^3) check over the trusted MXU rows, in place.

    time(2n)/time(n) under 4x (expected ~8x) demotes BOTH rows: a
    flattened curve means folding or an early-return barrier even when
    the absolute rates sit under the spec peak."""
    timed = [r for r in rows if "ms" in r]
    if len(timed) < 2:
        return
    lo, hi = min(timed, key=lambda r: r["n"]), max(timed, key=lambda r: r["n"])
    if hi["n"] != 2 * lo["n"]:
        return
    ratio = hi["ms"] / max(lo["ms"], 1e-9)
    if ratio < 4.0:
        msg = (f"scaling tripwire: time({hi['n']})/time({lo['n']}) = "
               f"{ratio:.2f}x, expected ~8x for O(n^3) — folding or "
               "early-return barrier")
        for r in (lo, hi):
            r["trusted"] = False
            r["suspect"] = True
            r["note"] = (r["note"] + "; " + msg) if r.get("note") else msg


def hbm_probe(size: int, iters: int, hard_sync, overhead_s: float,
              spec_gbps) -> dict:
    """Chunked, dispatch-corrected HBM read+write bandwidth at ``size``
    f32 elements."""
    import jax
    import jax.numpy as jnp
    x = jnp.ones((size,), jnp.float32)
    bytes_per_iter = 2 * 4 * size                  # read + write, f32
    scanned = _scan_fn(lambda y: y * 1.0001, iters)
    hard_sync(scanned(x))                          # compile + warm
    per_scan = _timed(hard_sync, scanned, x) / iters
    g = jax.jit(lambda y: y * 1.0001)
    y = hard_sync(g(x))
    t0 = time.perf_counter()
    for _ in range(iters):
        y = g(y)
    hard_sync(y)
    per_call = (time.perf_counter() - t0) / iters
    corrected = max(per_call - overhead_s, 1e-12)
    gbps = bytes_per_iter / per_scan / 1e9
    row = {"probe": f"hbm_rw_{4 * size // 2 ** 20}MiB", "iters": iters,
           "gbps": round(gbps, 1),
           "per_dispatch_gbps": round(bytes_per_iter / per_call / 1e9, 1),
           "dispatch_corrected_gbps":
               round(bytes_per_iter / corrected / 1e9, 1),
           "dispatch_overhead_ms": round(overhead_s * 1e3, 3),
           "trusted": True, "suspect": False,
           "spec_peak_gbps": spec_gbps}
    # the scan number is the gold one; the corrected per-dispatch number
    # cross-checks it — a large residual gap means the overhead model is
    # wrong (e.g. transfers overlap the next dispatch) and the probe is
    # demoted rather than published as a ceiling
    if spec_gbps and gbps > spec_gbps:
        row.update(trusted=False, suspect=True,
                   note=f"{gbps:.0f} GB/s exceeds the {spec_gbps} GB/s "
                        "spec peak: broken barrier or folded body")
    return row


def run(smoke: bool = False, sizes=None, hbm_sizes=None,
        iters: int = None) -> dict:
    import jax
    if smoke:
        # the axon plugin force-sets jax_platforms at interpreter boot —
        # without this pin a CI smoke run dials the tunnel
        jax.config.update("jax_platforms", "cpu")
    from bluefog_tpu.api import hard_sync
    from bluefog_tpu.utils.config import enable_compilation_cache
    enable_compilation_cache()
    bench = _bench_mod()
    d = jax.devices()[0]
    spec_peak = bench._peak_flops(d.device_kind)
    spec_gbps = bench._peak_hbm_gbps(d.device_kind)
    # smoke uses ONE size: at CPU-smoke shapes the timing is dispatch-bound,
    # so the O(n^3) scaling tripwire would fire on every healthy run
    if sizes is None:
        sizes = (256,) if smoke else (4096, 8192)
    if hbm_sizes is None:
        hbm_sizes = (2 ** 18,) if smoke else (2 ** 27, 2 ** 28)
    if iters is None:
        iters = 4 if smoke else 50
    mxu = [mxu_probe(n, iters, hard_sync, spec_peak) for n in sizes]
    apply_scaling_tripwire(mxu)
    overhead = _dispatch_overhead_s(hard_sync, max(iters * 4, 16))
    hbm = [hbm_probe(s, iters, hard_sync, overhead, spec_gbps)
           for s in hbm_sizes]
    return {
        "ok": True,
        "device": d.device_kind,
        "platform": d.platform,
        "smoke": smoke,
        "mxu": mxu,
        "hbm": hbm,
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes on whatever backend is attached")
    ap.add_argument("--out", default=None,
                    help="also write the JSON document here (atomic)")
    ap.add_argument("--sizes", default=None,
                    help="comma-separated MXU matmul sizes")
    ap.add_argument("--iters", type=int, default=None)
    args = ap.parse_args(argv)
    sizes = tuple(int(s) for s in args.sizes.split(",")) \
        if args.sizes else None
    doc = run(smoke=args.smoke, sizes=sizes, iters=args.iters)
    line = json.dumps(doc)
    print(line)
    if args.out:
        tmp = args.out + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(line + "\n")
        os.replace(tmp, args.out)
    trusted = [r for r in doc["mxu"] if r.get("trusted")]
    if not trusted and not args.smoke:
        # fail LOUDLY: a battery that banked an all-suspect roofline must
        # see a red step, not silently publish no ceiling
        print("roofline: every MXU probe failed a tripwire — no trusted "
              "ceiling was measured", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
