"""End-to-end grader for the decentralized serving engine.

Brings up the full train→serve estate on one device set: a gossip-DP
training fleet (``compose.make_train_step``) on the first ``train_dp``
slices and a :class:`bluefog_tpu.serve.ServeEngine` +
:class:`~bluefog_tpu.serve.Scheduler` on the rest, with a
:class:`~bluefog_tpu.serve.WeightRefresher` pulling fresh params
mid-traffic.  Grades serving on every axis ISSUE 10's claim rides on:

* **tokens/sec** of the continuous-batching drain (prefill + decode,
  training interleaved on the same host);
* **p50 / p99 per-token latency** from the
  ``bluefog_serve_token_latency_seconds`` histogram, plus TTFT
  percentiles from the completed requests themselves;
* **decode MFU** against the trusted roofline ceiling
  (``bench._peak_flops``; null off-TPU) using forward-only decode
  FLOPs/token (2N weight term + exact per-request attention context);
* **refresh staleness**: max and final value of the
  ``bluefog_serve_staleness_steps`` gauge, and the pull count — the
  freshness the gossip leaf actually delivered under load;
* **invariants**: KV-cache donation intact after the drain, retrace
  sentinel 0 after warmup (every served shape hit a declared bucket);

and, when the fast paths are armed (schema 2 rows):

* **speculative decoding** (``--spec-decode k[@stages]``): acceptance
  rate, accepted-tokens/s, and a bit-identity probe — the same prompts
  decoded by a plain-greedy reference engine must produce byte-identical
  token streams;
* **prefix sharing** (``--prefix-pages P[xT]``): hit/miss counts plus a
  same-prompt TTFT probe — the second, prefix-hit submission of an
  identical prompt must beat the cold one that sealed the page;
* **KV quantization** (``--kv-dtype int8|fp8``): KV bytes/token against
  the raw layout (the float64 logit-drift bound is pinned in
  ``tests/test_serve_fast.py``).

With ``--decode-kernel pallas[@block_k]`` (schema 4) the engine serves
through the paged flash-decode Pallas kernel (``ops/pallas_decode.py``)
instead of the XLA gather-then-attend path; the artifact gains a
``decode`` section with (a) a kernel-vs-XLA token bit-identity gate on
the same prompts and (b) decode-MFU-at-context rows — the decode
attention hot path timed at context x occupancy x KV-dtype points for
both the configured kernel and the XLA reference, with achieved
FLOPs/sec against the roofline ceiling.

With ``--serve-moe E[xK][@EP][:TILE]`` (schema 5) the whole estate goes
MoE: the training fleet optimizes a dropless routed-MoE LM
(``make_moe_grad_fn``) and the serving engine decodes through the
grouped-GEMM dropless path on an ``ep``-carved mesh, with the refresher
pulling router + expert tables live.  The artifact gains a ``moe``
section with (a) a greedy-token bit-identity gate (MoE speculative
decode vs plain MoE greedy), (b) tokens/s against the **dense twin at
equal active params** (``MoELMConfig.dense_twin`` — Switch-Transformer
accounting) on the same prompts, (c) the router-entropy / hot-expert
histogram the expert-load-aware scheduler reads, and (d) an AOT wire
proof — the fused-decode program's collectives classified per chip with
``stablehlo_wire_stats`` — gating that the dispatch/combine all_to_alls
are ICI-side (zero DCN all_to_alls).

With ``--traffic-trace`` (schema 3) the drain is followed by a bursty
traffic phase driven by a synthetic arrival trace (``diurnal`` — one
day-cycle sinusoid — or ``flash-crowd`` — a low base rate with a sudden
spike): the highest serve replica starts *parked* (out of rotation) and
an :class:`~bluefog_tpu.serve.scheduler.AutoScaler` watching queue depth
+ EWMA p99 must grow it back into the spike (writing the bfrun scale
file on the way) and retire it after the cooldown.  The artifact's
``trace`` row records the grow step, SLO recovery time (asserted under a
bound), scale events, and the requeued-vs-failed split — the gate
demands **zero failed requests** across the scale events.

Emits a ``bluefog-serve-bench-5`` JSON artifact (last stdout line, and
``--out``).

Run:    python tools/serve_bench.py --train-dp 2 --serve-dp 2 --pp 2 --out ...
Smoke:  python tools/serve_bench.py --virtual-cpu --smoke
Fast:   python tools/serve_bench.py --virtual-cpu --smoke \
            --spec-decode 3@1 --prefix-pages 2x8 --kv-dtype int8
Flash:  python tools/serve_bench.py --virtual-cpu --smoke \
            --decode-kernel pallas@8 --kv-dtype int8 --prefix-pages 2x8
MoE:    python tools/serve_bench.py --virtual-cpu --smoke \
            --serve-moe 4x2@2:4 --spec-decode 2@1
Trace:  python tools/serve_bench.py --virtual-cpu --smoke \
            --traffic-trace flash-crowd
"""
import argparse
import dataclasses
import importlib.util
import json
import os
import sys
import tempfile
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

SCHEMA = "bluefog-serve-bench-5"


def _trace_arrivals(shape, steps, slots, rng):
    """Per-step request arrival counts for a synthetic traffic shape.

    ``diurnal``: one full day cycle, midnight troughs and a midday peak
    sized to breach the queue-depth watermark.  ``flash-crowd``: a low
    base rate with a sudden spike of ``3*slots`` requests one third in.
    """
    import math
    if shape == "diurnal":
        # peak sized to oversubscribe ONE replica (forcing the grow) while
        # staying drainable by two before the recovery bound
        hi = max(4, (3 * slots) // 4)
        return [int(round(hi * 0.5 * (1.0 - math.cos(2.0 * math.pi
                                                     * t / steps))))
                for t in range(steps)]
    if shape == "flash-crowd":
        arrivals = [1] * steps
        arrivals[steps // 3] += 3 * slots
        return arrivals
    raise ValueError(f"unknown traffic shape {shape!r}")


def _run_traffic_trace(engine, shape, *, steps, vocab, max_new, rng,
                       slo_p99_ms=None):
    """The schema-3 bursty phase: parked reserve replica, arrival-trace
    traffic, and an AutoScaler that must grow into the spike.  Returns
    the artifact's ``trace`` row."""
    import tempfile
    from bluefog_tpu.diagnostics import SLOEngine
    from bluefog_tpu.run.launcher import _read_scale
    from bluefog_tpu.serve import Scheduler
    from bluefog_tpu.serve.scheduler import AutoScaler

    sched = Scheduler(engine)
    parked = [sched.replicas - 1] if sched.replicas >= 2 else []
    for r in parked:
        # no traffic yet: clean park (slice intact, eligible for re-admit)
        sched.fail_replica(r, reason="parked", park=True)
    scale_file = os.path.join(tempfile.mkdtemp(prefix="bfscale_"),
                              "bluefog_scale")
    scaler = AutoScaler(
        sched,
        slo_p99_s=(slo_p99_ms / 1000.0) if slo_p99_ms else None,
        queue_high=engine.scfg.slots,       # breach when one replica's
        cooldown_steps=3,                   # worth of slots is waiting
        scale_file=scale_file, min_replicas=1)
    # the SLO engine scores the same phase: burn-rate gauges every step,
    # fast-burn tripwire when the spike torches the error budget
    slo = SLOEngine(p99_ms=scaler.slo_p99_s * 1000.0)
    sched.attach_slo(slo)
    burn_peak = None
    arrivals = _trace_arrivals(shape, steps, engine.scfg.slots, rng)
    submitted = 0
    grow_step = None
    recovered_step = None
    t = 0

    def _tick():
        nonlocal grow_step, recovered_step, burn_peak
        sched.step()
        rate = slo.last_burn.get(("5m", "p99"))
        if rate is not None and (burn_peak is None or rate > burn_peak):
            burn_peak = rate
        ev = scaler.observe()
        if ev and ev["action"] == "grow" and grow_step is None:
            grow_step = t
        if (grow_step is not None and recovered_step is None
                and sched.pending == 0):
            recovered_step = t

    for t in range(steps):
        for _ in range(arrivals[t]):
            n = int(rng.integers(2, engine.scfg.prefill_buckets[-1] + 1))
            sched.submit(rng.integers(0, vocab, n).tolist(),
                         max_new_tokens=max_new)
            submitted += 1
        _tick()
    while not sched.done:
        t += 1
        if t > steps + 100_000:
            raise RuntimeError("traffic trace failed to drain")
        _tick()

    bound = 2 * steps
    recovery = (recovered_step - grow_step
                if grow_step is not None and recovered_step is not None
                else None)
    # the scale file speaks RANKS: live replicas x slice size.  Gate the
    # actual written value, not just its presence — a replica-count write
    # would make the supervisor SIGTERM ranks during the breach.
    scale_target = _read_scale(scale_file)
    expected_world = len(sched.live_replicas()) * engine.m.slice_size
    row = {
        "shape": shape,
        "steps": steps,
        "parked_replicas": parked,
        "submitted": submitted,
        "completed": len(sched.completed),
        "failed": len(sched.failed),
        "requeued": sched.requeued_total,
        "grow_step": grow_step,
        "recovery_steps": recovery,
        "recovery_bound_steps": bound,
        "slo_p99_s": scaler.slo_p99_s,
        "ewma_p99_s": scaler.ewma_p99,
        "slo": {
            "burn_peak_5m_p99": (round(burn_peak, 3)
                                 if burn_peak is not None else None),
            "burn_final": {f"{w}/{s}": (round(v, 3) if v is not None
                                        else None)
                           for (w, s), v in sorted(slo.last_burn.items())},
            "tripwires": sorted({f["kind"] for f in slo.fired}),
        },
        "scale_events": scaler.events,
        "scale_file_target": scale_target,
        "ranks_per_replica": engine.m.slice_size,
        "expected_world": expected_world,
        "ok": bool(submitted == len(sched.completed)
                   and not sched.failed
                   and grow_step is not None
                   and recovery is not None and recovery <= bound
                   and scale_target == expected_world
                   and (not scaler.events
                        or scale_target ==
                        scaler.events[-1]["target_world"])),
    }
    sched.close()
    return row


def _decode_attend_bench(scfg, heads, head_dim, *, kernel, block_k,
                         on_tpu, peak, iters):
    """Schema-4 decode-MFU-at-context rows.

    Times the decode attention hot path — one new token per lane over a
    slot-paged KV cache — at context x occupancy (live lanes) x KV-dtype
    points, for the configured kernel AND the XLA gather-then-attend
    reference on the same pages.  Attention FLOPs are exact (score +
    value matmuls over the attended context); MFU is against the trusted
    roofline ceiling, null off-TPU where interpret-mode Pallas timings
    grade nothing.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    from bluefog_tpu.ops import pallas_decode as _pd
    from bluefog_tpu.serve import kv_cache as _kv

    L, n_rows = scfg.max_len, scfg.slots + 1
    rng = np.random.default_rng(0)
    contexts = sorted({max(1, L // 4), max(1, L // 2), L})
    lanes = sorted({1, max(1, scfg.slots // 2), scfg.slots})
    dtypes = ["raw"] + ([scfg.kv_dtype] if scfg.kv_dtype != "raw" else [])

    def flash_fn(q, kl, vl, slots, lens, ksc, vsc):
        return _pd.flash_attend_rows(q, kl, vl, slots, lens,
                                     k_scale=ksc, v_scale=vsc,
                                     block_k=block_k)

    def xla_fn(q, kl, vl, slots, lens, ksc, vsc):
        return _kv.attend_rows(q, kl, vl, slots, lens,
                               k_scale=ksc, v_scale=vsc)

    fns = {"xla": jax.jit(xla_fn)}
    if kernel == "pallas":
        fns["pallas"] = jax.jit(flash_fn)

    def _time(fn, args):
        fn(*args).block_until_ready()           # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters

    rows = []
    for store in dtypes:
        kraw = jnp.asarray(
            rng.normal(size=(n_rows, heads, L, head_dim)), jnp.float32)
        vraw = jnp.asarray(
            rng.normal(size=(n_rows, heads, L, head_dim)), jnp.float32)
        if store == "raw":
            kl, vl, ksc, vsc = kraw, vraw, None, None
        else:
            kl, ksc = _kv.quantize_rows(kraw, store)
            vl, vsc = _kv.quantize_rows(vraw, store)
        for ctx in contexts:
            for S in lanes:
                q = jnp.asarray(
                    rng.normal(size=(S, heads, head_dim)), jnp.float32)
                slots = jnp.arange(S, dtype=jnp.int32)
                lens = jnp.full((S,), ctx - 1, jnp.int32)
                args = (q, kl, vl, slots, lens, ksc, vsc)
                walls = {name: _time(fn, args) for name, fn in fns.items()}
                flops = 4.0 * S * heads * head_dim * ctx
                wall = walls.get("pallas", walls["xla"])
                rows.append({
                    "kv_dtype": store,
                    "context": int(ctx),
                    "lanes": int(S),
                    "wall_us": round(wall * 1e6, 2),
                    "xla_wall_us": round(walls["xla"] * 1e6, 2),
                    "attn_flops": flops,
                    "flops_per_sec": round(flops / wall, 1) if wall else None,
                    "mfu": (round(flops / wall / peak, 8)
                            if on_tpu and peak and wall else None),
                })
    return rows


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name + "_mod", os.path.join(REPO, name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--virtual-cpu", action="store_true",
                    help="virtual CPU mesh sized (train_dp+serve_dp)*pp*tp"
                         "*ep (smoke/tests)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes for CI (implies quick compile)")
    ap.add_argument("--train-dp", type=int, default=2,
                    help="training gossip-DP replicas")
    ap.add_argument("--serve-dp", type=int, default=2,
                    help="serving replicas (engine gossip-DP axis)")
    ap.add_argument("--pp", type=int, default=1, help="pipeline stages")
    ap.add_argument("--tp", type=int, default=1, help="tensor-parallel ways")
    ap.add_argument("--layers", type=int, default=None)
    ap.add_argument("--d-model", type=int, default=None)
    ap.add_argument("--heads", type=int, default=None)
    ap.add_argument("--vocab", type=int, default=None)
    ap.add_argument("--requests", type=int, default=None,
                    help="concurrent requests to drain (default 16)")
    ap.add_argument("--max-new", type=int, default=None,
                    help="tokens generated per request (default 8)")
    ap.add_argument("--buckets", default=None,
                    help="'<batch,..>@<prompt_len,..>' serve shape buckets "
                         "(default from BLUEFOG_SERVE_BUCKETS or 1,2,4@8,16)")
    ap.add_argument("--slots", type=int, default=None,
                    help="KV slots per replica (default 8)")
    ap.add_argument("--max-len", type=int, default=None,
                    help="KV rows per slot (default 64)")
    ap.add_argument("--decode-steps-per-call", type=int, default=None,
                    help="fused decode steps per engine call (default 2)")
    ap.add_argument("--spec-decode", default=None,
                    help="self-speculative decoding: '<k>' or '<k>@<stages>'"
                         " draft depth / draft pipeline stages (default off)")
    ap.add_argument("--kv-dtype", default=None,
                    choices=("raw", "int8", "fp8"),
                    help="KV page storage (default raw)")
    ap.add_argument("--prefix-pages", default=None,
                    help="shared prefix pages: '<pages>' or "
                         "'<pages>x<page_tokens>' (default off)")
    ap.add_argument("--decode-kernel", default=None,
                    help="decode-attention backend: 'xla' or 'pallas' or "
                         "'pallas@<block_k>' (schema 4 row; default xla)")
    ap.add_argument("--serve-moe", default=None,
                    help="MoE estate: '<experts>[x<top_k>][@<ep>][:<tile>]'"
                         " e.g. '4x2@2:4' — dropless routed MoE trained and"
                         " served on ep-carved meshes (schema 5 row; "
                         "default BLUEFOG_SERVE_MOE or dense)")
    ap.add_argument("--traffic-trace", default=None,
                    choices=("diurnal", "flash-crowd"),
                    help="bursty traffic phase with a parked reserve "
                         "replica + SLO-driven autoscaling (schema 3 row)")
    ap.add_argument("--trace-steps", type=int, default=None,
                    help="scheduler steps in the traffic trace (default 24)")
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="autoscaler p99 SLO (default BLUEFOG_SLO_P99_MS "
                         "or 250)")
    ap.add_argument("--train-steps", type=int, default=None,
                    help="train steps interleaved with serving (default 6)")
    ap.add_argument("--refresh-every", type=int, default=None,
                    help="pull fresh weights every N train steps "
                         "(default from BLUEFOG_REFRESH_EVERY or 2)")
    ap.add_argument("--out", default=None, help="json artifact path")
    ap.add_argument("--allow-cpu", action="store_true")
    args = ap.parse_args()

    if args.serve_moe is None:
        args.serve_moe = os.environ.get("BLUEFOG_SERVE_MOE") or None
    # ep widens the slice, so it must enter the chip math before jax
    # initializes; only the @ep token is read here — the full grammar is
    # validated by engine._parse_serve_moe once the libraries are up
    moe_ep = 1
    if args.serve_moe:
        ep_s = args.serve_moe.partition(":")[0].partition("@")[2]
        if ep_s.isdigit():
            moe_ep = int(ep_s)
    n_chips = (args.train_dp + args.serve_dp) * args.pp * args.tp * moe_ep
    if args.virtual_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{n_chips}").strip()
    import jax
    if args.virtual_cpu:
        jax.config.update("jax_platforms", "cpu")

    from bluefog_tpu.utils.config import enable_compilation_cache
    enable_compilation_cache()

    dev = jax.devices()[0]
    on_tpu = jax.default_backend() == "tpu"
    if dev.platform == "cpu" and not (args.virtual_cpu or args.allow_cpu):
        print("refusing: no accelerator (pass --virtual-cpu or --allow-cpu)",
              file=sys.stderr)
        sys.exit(2)
    if len(jax.devices()) < n_chips:
        raise SystemExit(
            f"need {n_chips} devices for (train_dp+serve_dp)*pp*tp*ep, "
            f"have {len(jax.devices())}")

    smoke = args.smoke or (args.virtual_cpu and not on_tpu)
    layers = args.layers or (args.pp * (2 if smoke else 2))
    d_model = args.d_model or (32 if smoke else 1024)
    heads = args.heads or (4 if smoke else 16)
    vocab = args.vocab or (64 if smoke else 32768)
    n_requests = args.requests or 16
    max_new = args.max_new or 8
    slots = args.slots or 8
    max_len = args.max_len or 64
    steps_per_call = args.decode_steps_per_call or 2
    train_steps = args.train_steps if args.train_steps is not None else 6
    refresh_every = args.refresh_every
    if refresh_every is None and smoke and "BLUEFOG_REFRESH_EVERY" not in \
            os.environ:
        refresh_every = 2

    import numpy as np
    import optax
    import bluefog_tpu.optimizers as bfopt
    from bluefog_tpu.parallel import compose
    from bluefog_tpu.serve import (ServeConfig, ServeEngine, Scheduler,
                                   WeightRefresher)
    from bluefog_tpu.serve.engine import _parse_buckets
    from bluefog_tpu.utils import metrics as bfm
    from bluefog_tpu.utils import tracing as _tracing

    # arm request tracing before any scheduler exists so every request in
    # the drain gets a span tree; the bundle feeds the latency-breakdown
    # block at the end (BLUEFOG_TRACE wins if the operator set it)
    trace_dir = os.environ.get(_tracing.ENV_TRACE) or tempfile.mkdtemp(
        prefix="bftrace_")
    _tracing.configure(trace_dir)

    devs = jax.devices()
    slice_sz = args.pp * args.tp * moe_ep
    train_devs = devs[:args.train_dp * slice_sz]
    serve_devs = devs[args.train_dp * slice_sz:n_chips]

    lm_kw = dict(vocab=vocab, d_model=d_model, heads=heads, layers=layers,
                 seq_len=32 if smoke else 128, micro=max(2 * args.pp, 2))
    if args.serve_moe:
        from bluefog_tpu.moe.model import (MoELMConfig, init_moe_params,
                                           make_moe_batch, make_moe_grad_fn)
        from bluefog_tpu.serve.engine import _parse_serve_moe
        moe_E, moe_k, moe_ep_full, moe_tile = _parse_serve_moe(
            args.serve_moe)
        if moe_ep_full != moe_ep:
            raise SystemExit(f"--serve-moe ep token {moe_ep_full} did not "
                             f"survive the chip-math pre-parse ({moe_ep})")
        cfg = MoELMConfig(batch=2 * moe_ep, num_experts=moe_E, top_k=moe_k,
                          dispatch="dropless", **lm_kw)
        train_m = compose.compose_parallelism(
            args.train_dp, args.pp, args.tp, 1, moe_ep, devices=train_devs,
            num_experts=moe_E)
        serve_m = compose.compose_parallelism(
            args.serve_dp, args.pp, args.tp, 1, moe_ep, devices=serve_devs,
            num_experts=moe_E)
    else:
        cfg = compose.LMConfig(batch=2, **lm_kw)
        train_m = compose.compose_parallelism(
            args.train_dp, args.pp, args.tp, 1, devices=train_devs)
        serve_m = compose.compose_parallelism(
            args.serve_dp, args.pp, args.tp, 1, devices=serve_devs)
    cfg.validate(train_m)

    sc_kw = dict(slots=slots, max_len=max_len,
                 decode_steps_per_call=steps_per_call)
    if args.spec_decode:
        k_s, _, st_s = args.spec_decode.partition("@")
        sc_kw["spec_decode"] = int(k_s)
        if st_s:
            sc_kw["spec_stages"] = int(st_s)
    if args.kv_dtype:
        sc_kw["kv_dtype"] = args.kv_dtype
    if args.decode_kernel:
        kern, _, bk_s = args.decode_kernel.partition("@")
        sc_kw["decode_kernel"] = kern       # ServeConfig validates the token
        if bk_s:
            sc_kw["decode_block_k"] = int(bk_s)
    if args.prefix_pages:
        pg_s, _, pt_s = args.prefix_pages.partition("x")
        sc_kw["prefix_pages"] = int(pg_s)
        if pt_s:
            sc_kw["prefix_page_tokens"] = int(pt_s)
    if args.serve_moe:
        sc_kw.update(moe_experts=moe_E, moe_top_k=moe_k, moe_ep=moe_ep,
                     moe_tile=moe_tile)
    if args.buckets:
        bb, pb = _parse_buckets(args.buckets)
        scfg = ServeConfig(batch_buckets=bb, prefill_buckets=pb, **sc_kw)
    else:
        scfg = ServeConfig.from_env(**sc_kw)

    # -- training fleet -----------------------------------------------------
    if args.serve_moe:
        grad_fn = make_moe_grad_fn(cfg, train_m)
        train_params = init_moe_params(cfg, train_m, seed=1)
        toks = make_moe_batch(cfg, train_m)
    else:
        grad_fn = compose.make_lm_grad_fn(cfg, train_m)
        train_params = compose.init_lm_params(cfg, train_m, seed=1)
        toks = compose.make_lm_batch(cfg, train_m)
    step, strategy = compose.make_train_step(
        train_m, grad_fn, optax.adam(5e-3))
    state = bfopt.init_distributed(strategy, train_params)
    train_params = compose.device_put(train_m, train_params)

    # -- serving fleet ------------------------------------------------------
    serve_params = (init_moe_params(cfg, serve_m, seed=0) if args.serve_moe
                    else compose.init_lm_params(cfg, serve_m, seed=0))
    engine = ServeEngine(serve_m, cfg, serve_params, scfg)
    engine.warmup()

    rng = np.random.default_rng(0)

    def _drain_tokens(eng, prompts):
        """Drain ``prompts`` through a throwaway scheduler; per-request
        token streams (probe harness — closed before the traffic run)."""
        s = Scheduler(eng)
        reqs = [s.submit(p, max_new_tokens=max_new) for p in prompts]
        s.drain()
        s.close()
        return reqs

    # probe (a): speculative bit-identity — the same prompts through a
    # plain-greedy reference engine must produce identical token streams
    spec_probe = None
    if scfg.spec_decode:
        probe_prompts = [rng.integers(0, vocab, int(rng.integers(
            2, scfg.prefill_buckets[-1] + 1))).tolist() for _ in range(3)]
        ref_eng = ServeEngine(serve_m, cfg, serve_params,
                              dataclasses.replace(scfg, spec_decode=0))
        ref_eng.warmup()
        ref = [r.generated for r in _drain_tokens(ref_eng, probe_prompts)]
        got = [r.generated for r in _drain_tokens(engine, probe_prompts)]
        spec_probe = {"prompts": len(probe_prompts),
                      "bit_identical": bool(ref == got)}
        del ref_eng

    # probe (b): prefix-hit TTFT — an identical prompt submitted twice;
    # the first seals the shared page (cold), the second attaches (hit)
    prefix_probe = None
    if scfg.prefix_pages:
        ptoks = scfg.prefix_page_tokens
        shared = rng.integers(0, vocab, ptoks).tolist()
        probe_prompt = shared + rng.integers(
            0, vocab, max(1, min(4, scfg.prefill_buckets[-1] - ptoks))
        ).tolist()
        cold = _drain_tokens(engine, [probe_prompt])[0]
        hit = _drain_tokens(engine, [probe_prompt])[0]
        prefix_probe = {
            "ttft_cold_s": round(cold.ttft, 6),
            "ttft_hit_s": round(hit.ttft, 6),
            "hit_prefix_len": hit.prefix_len,
            "hit_faster": bool(hit.ttft < cold.ttft),
            "tokens_identical": bool(cold.generated == hit.generated)}
    else:
        shared = None

    # probe (c): flash-decode bit-identity — the pallas-kernel engine must
    # emit the same greedy token streams as the XLA gather-then-attend path
    flash_probe = None
    if scfg.decode_kernel == "pallas":
        probe_prompts = [rng.integers(0, vocab, int(rng.integers(
            2, scfg.prefill_buckets[-1] + 1))).tolist() for _ in range(3)]
        ref_eng = ServeEngine(serve_m, cfg, serve_params,
                              dataclasses.replace(scfg, decode_kernel="xla"))
        ref_eng.warmup()
        ref = [r.generated for r in _drain_tokens(ref_eng, probe_prompts)]
        got = [r.generated for r in _drain_tokens(engine, probe_prompts)]
        flash_probe = {"prompts": len(probe_prompts),
                       "bit_identical": bool(ref == got)}
        del ref_eng

    # probe (d), schema 5: MoE serving — greedy bit-identity through the
    # speculative path, the dense twin at equal ACTIVE params timed on
    # the same prompts, and the AOT wire split of the fused decode
    moe_probe = None
    if args.serve_moe:
        from bluefog_tpu.utils.hlo_bytes import stablehlo_wire_stats

        def _timed_tps(eng, prompts):
            before = bfm.counter("bluefog_tokens_generated_total").total()
            w0 = time.perf_counter()
            _drain_tokens(eng, prompts)
            wall = time.perf_counter() - w0
            made = bfm.counter(
                "bluefog_tokens_generated_total").total() - before
            return (made / wall) if wall > 0 else None

        moe_prompts = [rng.integers(0, vocab, int(rng.integers(
            2, scfg.prefill_buckets[-1] + 1))).tolist() for _ in range(8)]
        if spec_probe is not None:
            bit = dict(spec_probe)          # spec-MoE vs plain-greedy-MoE
        else:
            spec_eng = ServeEngine(
                serve_m, cfg, serve_params,
                dataclasses.replace(scfg, spec_decode=2, spec_stages=1))
            spec_eng.warmup()
            got = [r.generated
                   for r in _drain_tokens(spec_eng, moe_prompts[:3])]
            ref = [r.generated
                   for r in _drain_tokens(engine, moe_prompts[:3])]
            bit = {"prompts": 3, "bit_identical": bool(ref == got)}
            del spec_eng
        # the fair baseline: same skeleton, ffn_mult scaled by top_k —
        # equal FLOPs per token, 1/E-th the FFN capacity per chip set
        dense_cfg = cfg.dense_twin()
        dense_m = compose.compose_parallelism(
            args.serve_dp, args.pp, args.tp, 1,
            devices=serve_devs[:args.serve_dp * args.pp * args.tp])
        dense_eng = ServeEngine(
            dense_m, dense_cfg,
            compose.init_lm_params(dense_cfg, dense_m, seed=0),
            dataclasses.replace(scfg, moe_experts=0, moe_top_k=1,
                                moe_ep=1, moe_tile=0))
        dense_eng.warmup()
        moe_tps = _timed_tps(engine, moe_prompts)
        dense_tps = _timed_tps(dense_eng, moe_prompts)
        del dense_eng
        moe_probe = {
            "bit": bit, "tps_moe": moe_tps, "tps_dense": dense_tps,
            "dense_n_params": dense_cfg.n_params,
            "wire": stablehlo_wire_stats(engine.decode_lowered_text(),
                                         serve_m.slice_size),
        }

    refresher = WeightRefresher(engine, train_m, every=refresh_every)
    sched = Scheduler(engine)
    cache_probe = engine.cache["k"]       # donated into the first decode

    spec0 = {n: bfm.counter(n).total() for n in
             ("bluefog_serve_spec_drafted_total",
              "bluefog_serve_spec_accepted_total")}
    hitmiss0 = {n: bfm.counter(n).total() for n in
                ("bluefog_serve_prefix_hits_total",
                 "bluefog_serve_prefix_misses_total")}
    tokens0 = bfm.counter("bluefog_tokens_generated_total").total()

    prompt_lens = []
    for i in range(n_requests):
        if shared is not None and i % 2 == 0:
            # the million-user shape: half the traffic reuses one system
            # prompt — its page seals once per replica and then every
            # admission is a remainder-only chunk prefill
            room = scfg.prefill_buckets[-1] - len(shared)
            p = shared + rng.integers(
                0, vocab, int(rng.integers(1, room + 1))).tolist()
        else:
            n = int(rng.integers(2, scfg.prefill_buckets[-1] + 1))
            p = rng.integers(0, vocab, n).tolist()
        prompt_lens.append(len(p))
        sched.submit(p, max_new_tokens=max_new)

    # -- interleaved drain: serve steps with training advancing live --------
    stal_max, pulls, train_done = 0.0, 0, 0
    t0 = time.perf_counter()
    guard = 0
    while not sched.done:
        guard += 1
        if guard > 100_000:
            raise RuntimeError("scheduler failed to drain")
        sched.step()
        if train_done < train_steps:
            train_params, state, _ = step(train_params, state, toks)
            train_done += 1
            refresher.note_train_step(train_done)
            stal_max = max(stal_max, refresher.staleness() or 0.0)
            if refresher.maybe_refresh(train_params, train_done):
                pulls += 1
    dt = time.perf_counter() - t0
    stal_final = refresher.staleness()

    # probes above generate tokens too — tokens/s uses the timed-drain delta
    tokens = int(bfm.counter("bluefog_tokens_generated_total").total()
                 - tokens0)
    tok_per_sec = tokens / dt if dt > 0 else None

    # -- bursty traffic + autoscaling phase (schema 3) ----------------------
    trace_doc = None
    if args.traffic_trace:
        trace_doc = _run_traffic_trace(
            engine, args.traffic_trace, steps=args.trace_steps or 24,
            vocab=vocab, max_new=max_new, rng=rng,
            slo_p99_ms=args.slo_p99_ms)

    lat = bfm.get_metric("bluefog_serve_token_latency_seconds")
    ttfts = sorted(r.ttft for r in sched.completed if r.ttft is not None)

    # decode FLOPs/token: forward weight term + the exact attention
    # context each generated token attended over (score + value matmuls)
    n_tok, ctx_sum = 0, 0
    for req in sched.completed:
        p = len(req.prompt)
        for i in range(len(req.generated)):
            n_tok += 1
            ctx_sum += p + i
    avg_ctx = (ctx_sum / n_tok) if n_tok else 0.0
    # MoE: the weight term counts ACTIVE params only — a decoded token
    # touches its top-k experts, not the full table
    n_weight = getattr(cfg, "n_active_params", cfg.n_params)
    decode_flops_per_token = (2.0 * n_weight
                              + 4.0 * cfg.layers * cfg.d_model * avg_ctx)
    bench = _load_tool("bench")
    peak = bench._peak_flops(dev.device_kind) if on_tpu else None
    serve_chips = args.serve_dp * slice_sz

    retraces = int(bfm.counter("bluefog_retrace_after_warmup_total").total())

    # -- fast-path rows (schema 2) ------------------------------------------
    spec_doc = None
    if scfg.spec_decode:
        drafted = int(bfm.counter("bluefog_serve_spec_drafted_total").total()
                      - spec0["bluefog_serve_spec_drafted_total"])
        accepted = int(
            bfm.counter("bluefog_serve_spec_accepted_total").total()
            - spec0["bluefog_serve_spec_accepted_total"])
        spec_doc = {
            "k": scfg.spec_decode,
            "stages": scfg.spec_stages,
            "cost_fraction": round(engine.draft.cost_fraction, 4),
            "drafted": drafted,
            "accepted": accepted,
            "acceptance_rate": (round(accepted / drafted, 4)
                                if drafted else None),
            "accepted_tokens_per_sec": (round(accepted / dt, 1)
                                        if dt > 0 else None),
            **spec_probe,
        }
    prefix_doc = None
    if scfg.prefix_pages:
        hits = int(bfm.counter("bluefog_serve_prefix_hits_total").total()
                   - hitmiss0["bluefog_serve_prefix_hits_total"])
        misses = int(bfm.counter("bluefog_serve_prefix_misses_total").total()
                     - hitmiss0["bluefog_serve_prefix_misses_total"])
        prefix_doc = {
            "pages": scfg.prefix_pages,
            "page_tokens": scfg.prefix_page_tokens,
            "hits": hits,
            "misses": misses,
            **prefix_probe,
        }
    kv_doc = None
    if engine.cache_cfg.quantized:
        bpt = engine.cache_cfg.bytes_per_token()
        raw_bpt = dataclasses.replace(
            engine.cache_cfg, store="raw").bytes_per_token()
        kv_doc = {
            "dtype": scfg.kv_dtype,
            "bytes_per_token": bpt,
            "raw_bytes_per_token": raw_bpt,
            "ratio": round(bpt / raw_bpt, 4),
        }

    # -- flash-decode rows (schema 4) ----------------------------------------
    decode_doc = None
    if scfg.decode_kernel == "pallas":
        decode_doc = {
            "kernel": scfg.decode_kernel,
            "block_k": scfg.decode_block_k,
            **flash_probe,
            "attend": _decode_attend_bench(
                scfg, heads, d_model // heads, kernel=scfg.decode_kernel,
                block_k=scfg.decode_block_k, on_tpu=on_tpu, peak=peak,
                iters=3 if smoke else 20),
        }

    # -- MoE serving rows (schema 5) -----------------------------------------
    moe_doc = None
    if moe_probe is not None:
        ws = moe_probe["wire"]
        a2a_ici = ws["ici"].get("all_to_all", {"count": 0, "bytes": 0})
        a2a_dcn = ws["dcn"].get("all_to_all", {"count": 0, "bytes": 0})
        live = [row for row in (engine.moe_load() or []) if row["tokens"]]
        hist = (np.mean([row["fractions"] for row in live], axis=0)
                if live else np.zeros(scfg.moe_experts))
        tps_m, tps_d = moe_probe["tps_moe"], moe_probe["tps_dense"]
        moe_doc = {
            "experts": scfg.moe_experts,
            "top_k": scfg.moe_top_k,
            "ep": scfg.moe_ep,
            "tile": engine._moe_tile,
            "n_params_total": cfg.n_params,
            "n_params_active": cfg.n_active_params,
            "dense_twin_n_params": moe_probe["dense_n_params"],
            "tokens_per_sec_moe": round(tps_m, 1) if tps_m else None,
            "tokens_per_sec_dense_twin": (round(tps_d, 1)
                                          if tps_d else None),
            "vs_dense_equal_active": (round(tps_m / tps_d, 4)
                                      if tps_m and tps_d else None),
            "serve_chips_moe": args.serve_dp * slice_sz,
            "serve_chips_dense_twin": args.serve_dp * args.pp * args.tp,
            "bit_identity": moe_probe["bit"],
            "router_entropy_mean": (round(float(np.mean(
                [row["entropy"] for row in live])), 4) if live else None),
            "hot_expert": {
                "counts": [int(c) for c in (np.sum(
                    [row["counts"] for row in live], axis=0) if live
                    else np.zeros(scfg.moe_experts))],
                "fractions": [round(float(f), 4) for f in hist],
                "max_fraction": (round(float(hist.max()), 4)
                                 if len(hist) else None),
            },
            "wire": {
                "per_chip_ici_bytes": ws["ici_bytes"],
                "per_chip_dcn_bytes": ws["dcn_bytes"],
                "all_to_all_ici": a2a_ici,
                "all_to_all_dcn": a2a_dcn,
            },
        }

    # -- per-request latency breakdown from the tracer ----------------------
    breakdown_doc = None
    bundle = _tracing.flush()
    if bundle:
        tr = _load_tool("tools/trace_report")
        tr_doc, _ = tr.report_from_files([bundle])
        reqs_tr = tr_doc["requests"]
        if reqs_tr:
            def _mean(key):
                return round(sum(v[key] for v in reqs_tr.values())
                             / len(reqs_tr), 6)
            breakdown_doc = {
                "n_requests": len(reqs_tr),
                "queue_mean_s": _mean("queue_s"),
                "prefill_mean_s": _mean("prefill_s"),
                "decode_mean_s": _mean("decode_s"),
                "gap_mean_s": _mean("gap_s"),
                "slowest": [[t, round(total, 6)] for t, total, *_ in
                            tr_doc["critical_path"][:5]],
                "bundle": bundle,
            }

    doc = {
        "schema": SCHEMA,
        "ok": True,
        "on_accelerator": on_tpu,
        "device": dev.device_kind,
        "serve": {"replicas": args.serve_dp, "pp": args.pp, "tp": args.tp,
                  "slots": slots, "max_len": max_len,
                  "decode_steps_per_call": steps_per_call,
                  "batch_buckets": list(scfg.batch_buckets),
                  "prefill_buckets": list(scfg.prefill_buckets),
                  "kv_dtype": scfg.kv_dtype,
                  "kv_cache_bytes": engine.cache_cfg.bytes(),
                  "kv_bytes_per_token": engine.cache_cfg.bytes_per_token()},
        "train": {"replicas": args.train_dp, "steps": train_done},
        "config": {"d_model": d_model, "heads": heads, "layers": layers,
                   "vocab": vocab, "n_params": cfg.n_params},
        "requests": {"submitted": n_requests,
                     "completed": len(sched.completed),
                     "failed": len(sched.failed),
                     "max_new_tokens": max_new,
                     "tokens_generated": tokens,
                     "avg_prompt_len": round(float(np.mean(prompt_lens)), 2)},
        "wall_s": round(dt, 4),
        "tokens_per_sec": round(tok_per_sec, 1) if tok_per_sec else None,
        "latency": {
            "per_token_p50_s": (round(lat.percentile(0.5), 6)
                                if lat is not None else None),
            "per_token_p99_s": (round(lat.percentile(0.99), 6)
                                if lat is not None else None),
            "ttft_p50_s": (round(ttfts[len(ttfts) // 2], 6)
                           if ttfts else None),
            "ttft_max_s": round(ttfts[-1], 6) if ttfts else None,
        },
        "mfu": {"decode_flops_per_token": round(decode_flops_per_token, 1),
                "avg_context": round(avg_ctx, 1),
                "model_flops_per_sec": (
                    round(tok_per_sec * decode_flops_per_token, 1)
                    if tok_per_sec else None),
                "peak_flops_per_chip": peak,
                "mfu": (round(tok_per_sec * decode_flops_per_token
                              / (peak * serve_chips), 6)
                        if peak and tok_per_sec else None)},
        "refresh": {"every": refresher.every, "pulls": pulls,
                    "staleness_max_steps": stal_max,
                    "staleness_final_steps": stal_final},
        "spec": spec_doc,
        "prefix": prefix_doc,
        "kv": kv_doc,
        "decode": decode_doc,
        "moe": moe_doc,
        "trace": trace_doc,
        "latency_breakdown": breakdown_doc,
        "invariants": {
            "donation_intact": bool(cache_probe.is_deleted()),
            "retraces_after_warmup": retraces,
        },
    }
    fast_ok = True
    if spec_doc is not None:
        fast_ok &= spec_doc["bit_identical"]
    if prefix_doc is not None:
        fast_ok &= bool(prefix_doc["hit_faster"]
                        and prefix_doc["tokens_identical"]
                        and prefix_doc["hits"] >= 1)
    if kv_doc is not None and scfg.kv_dtype == "int8":
        fast_ok &= kv_doc["ratio"] <= 0.5
    if decode_doc is not None:
        fast_ok &= decode_doc["bit_identical"]
    if moe_doc is not None:
        # the ISSUE 19 gate: spec-vs-greedy token identity, a measured
        # dense-twin comparison, and dispatch/combine a2a traffic that is
        # entirely intra-slice (ICI) — any DCN all_to_all fails the run
        fast_ok &= bool(moe_doc["bit_identity"]["bit_identical"]
                        and moe_doc["tokens_per_sec_moe"]
                        and moe_doc["tokens_per_sec_dense_twin"]
                        and moe_doc["wire"]["all_to_all_ici"]["count"] >= 1
                        and moe_doc["wire"]["all_to_all_dcn"]["count"] == 0)
    doc["ok"] = bool(len(sched.completed) == n_requests
                     and doc["invariants"]["donation_intact"]
                     and retraces == 0
                     and fast_ok
                     and (trace_doc is None or trace_doc["ok"])
                     and (train_steps == 0 or pulls >= 1))
    sched.close()
    _emit(doc, args.out)


def _emit(doc, out):
    if out:
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w") as f:
            json.dump(doc, f, indent=1)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
