"""Sequence-parallel microbenchmark: ring layouts vs ulysses all-to-all.

On the virtual CPU mesh all 8 emulated devices share one core, so wall
clock tracks TOTAL work — which exposes the zigzag saving directly: the
contiguous causal ring computes (and then masks) every K/V block on every
device, while zigzag computes exactly the visible half.  On real TPU the
same factor shows up as wall clock through load balance (the contiguous
ring's critical path is the last device computing all n blocks).  The
ulysses row re-shards with 2 all_to_alls and runs dense local attention —
fewer, bigger collectives; compare when heads >= devices.

Run: python tools/sp_bench.py --virtual-cpu [--seq 4096] [--iters 5]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--virtual-cpu", action="store_true")
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--heads", type=int, default=8)   # divisible by the
                                                      # 8-device mesh so the
                                                      # ulysses row runs
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args()

    if args.virtual_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    if args.virtual_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P
    import bluefog_tpu as bf
    from bluefog_tpu.ops import ring_attention

    bf.init(platform="cpu" if args.virtual_cpu else None)
    n = bf.size()
    B, T, H, D = 1, args.seq, args.heads, args.head_dim
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
               for _ in range(3))

    def build(layout):
        def f(qb, kb, vb):
            return ring_attention(qb, kb, vb, axis="rank", causal=True,
                                  layout=layout)
        return jax.jit(jax.shard_map(
            f, mesh=bf.mesh(), in_specs=(P(None, "rank"),) * 3,
            out_specs=P(None, "rank")))

    def build_ulysses():
        from bluefog_tpu.ops import ulysses_attention

        def f(qb, kb, vb):
            return ulysses_attention(qb, kb, vb, axis="rank", causal=True)
        return jax.jit(jax.shard_map(
            f, mesh=bf.mesh(), in_specs=(P(None, "rank"),) * 3,
            out_specs=P(None, "rank")))

    print(f"causal attention, seq {T} over {n} devices "
          f"({T // n}/device), {H} heads x {D}:")
    modes = [("contiguous", build("contiguous")), ("zigzag", build("zigzag"))]
    if H % n == 0:
        modes.append(("ulysses", build_ulysses()))
    else:
        print(f"  (ulysses skipped: heads {H} not divisible by {n} devices)")
    for name, fn in modes:
        out = bf.hard_sync(fn(q, k, v))          # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(q, k, v)
        bf.hard_sync(out)
        ms = (time.perf_counter() - t0) / args.iters * 1e3
        print(f"  {name:>11}: {ms:8.1f} ms/step")


if __name__ == "__main__":
    main()
