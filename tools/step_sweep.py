"""Dispatch-amortization sweep + step trace for the headline benchmark.

Runs bench.py's exact measurement (``bench.run_bench``) at several
``steps_per_call`` values on the attached accelerator, showing how scanning
K optimizer steps into one compiled program amortizes the host->device
dispatch cost (the per-call overhead measured by ``tools/chip_calibrate.py``).
Optionally captures a profiler trace of the steady-state step for the
compute/comm/host attribution in docs/PERFORMANCE.md.

Run (single tunnel client):
    python tools/step_sweep.py [--trace /tmp/bench_trace] \
        [--out docs/measured/step_sweep_r03.json]
"""
import argparse
import importlib.util
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _summarize(device_kind, batch, rows, partial):
    """vs_spc1/amortization from whatever rows exist so far (the k=1
    baseline runs first in the sorted sweep) — single home for the
    formula, shared by the stdout summary and the JSON artifact."""
    base = rows[0]["imgs_per_sec_per_chip"]
    rows = [dict(r, vs_spc1=round(r["imgs_per_sec_per_chip"] / base, 3))
            for r in rows]
    summary = {"device": device_kind, "batch": batch, "rows": rows,
               "dispatch_amortization":
                   round(max(r["imgs_per_sec_per_chip"] for r in rows)
                         / base, 3)}
    if partial:
        summary["partial"] = True      # sweep did not finish all k values
    return summary


def _write_summary(out, device_kind, batch, rows, partial):
    summary = _summarize(device_kind, batch, rows, partial)
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    tmp = f"{out}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(summary, f, indent=1)
    os.replace(tmp, out)
    return summary


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--sweep", default="1,2,5,10",
                        help="comma-separated steps_per_call values")
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--trace", default=None,
                        help="directory for a jax.profiler trace of the "
                             "largest steps_per_call run")
    parser.add_argument("--out", default=None, help="json artifact path")
    parser.add_argument("--allow-cpu", action="store_true")
    args = parser.parse_args()

    import jax

    from bluefog_tpu.utils.config import enable_compilation_cache
    if args.allow_cpu:
        # the axon plugin force-sets jax_platforms at boot; without this a
        # CPU smoke dials the TPU tunnel
        jax.config.update("jax_platforms", "cpu")
    enable_compilation_cache()      # after the platform pin: no-op on CPU
    dev = jax.devices()[0]
    if dev.platform == "cpu" and not args.allow_cpu:
        print("refusing: no accelerator (pass --allow-cpu to force)",
              file=sys.stderr)
        sys.exit(2)

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), os.pardir,
                              "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    sweep = [int(s) for s in args.sweep.split(",")]
    on_accel = dev.platform != "cpu"
    os.environ["BLUEFOG_BENCH_BATCH"] = str(args.batch)
    os.environ["BLUEFOG_BENCH_ITERS"] = str(args.iters)

    rows = []
    for i, spc in enumerate(sorted(sweep)):
        os.environ["BLUEFOG_BENCH_STEPS_PER_CALL"] = str(spc)
        tracing = args.trace and spc == max(sweep)
        if tracing:
            # a profiler failure (the axon PJRT plugin may not support
            # device tracing through the tunnel) must not cost the sweep
            # rows themselves — they are the artifact; the trace is a bonus
            try:
                jax.profiler.start_trace(args.trace)
            except Exception as e:          # noqa: BLE001
                print(f"step_sweep: start_trace failed ({e}); continuing "
                      "without a trace", file=sys.stderr)
                tracing = False
        r = bench.run_bench(on_accel, {"sweep_index": i})
        if tracing:
            try:
                jax.profiler.stop_trace()
            except Exception as e:          # noqa: BLE001
                print(f"step_sweep: stop_trace failed ({e}); trace "
                      "may be partial", file=sys.stderr)
        row = {"steps_per_call": spc, "imgs_per_sec_per_chip": r["value"],
               "mfu": r["mfu"]}
        rows.append(row)
        print(json.dumps(row), flush=True)
        # bank INCREMENTALLY: a tunnel death mid-sweep (observed round 5)
        # kills the process group and loses the stdout pipe — rows already
        # measured must survive in the artifact.  partial is positional:
        # only the LAST iteration's write claims a complete sweep.
        if args.out:
            summary = _write_summary(args.out, dev.device_kind, args.batch,
                                     rows, partial=i != len(sweep) - 1)

    if not args.out:
        summary = _summarize(dev.device_kind, args.batch, rows,
                             partial=False)
    print(json.dumps({"summary": summary["dispatch_amortization"],
                      "best": max(summary["rows"],
                                  key=lambda r: r["imgs_per_sec_per_chip"])}))
    if args.out:
        print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
