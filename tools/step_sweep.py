"""Dispatch-amortization sweep + step trace for the headline benchmark.

Runs bench.py's exact measurement (``bench.run_bench``) at several
``steps_per_call`` values on the attached accelerator, showing how scanning
K optimizer steps into one compiled program amortizes the host->device
dispatch cost (the per-call overhead measured by ``tools/chip_calibrate.py``).
Optionally captures a profiler trace of the steady-state step for the
compute/comm/host attribution in docs/PERFORMANCE.md.

Run (single tunnel client):
    python tools/step_sweep.py [--trace /tmp/bench_trace] \
        [--out docs/measured/step_sweep_r03.json]
"""
import argparse
import importlib.util
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--sweep", default="1,2,5,10",
                        help="comma-separated steps_per_call values")
    parser.add_argument("--batch", type=int, default=64)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--trace", default=None,
                        help="directory for a jax.profiler trace of the "
                             "largest steps_per_call run")
    parser.add_argument("--out", default=None, help="json artifact path")
    parser.add_argument("--allow-cpu", action="store_true")
    args = parser.parse_args()

    import jax

    from bluefog_tpu.utils.config import enable_compilation_cache
    if args.allow_cpu:
        # the axon plugin force-sets jax_platforms at boot; without this a
        # CPU smoke dials the TPU tunnel
        jax.config.update("jax_platforms", "cpu")
    enable_compilation_cache()      # after the platform pin: no-op on CPU
    dev = jax.devices()[0]
    if dev.platform == "cpu" and not args.allow_cpu:
        print("refusing: no accelerator (pass --allow-cpu to force)",
              file=sys.stderr)
        sys.exit(2)

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), os.pardir,
                              "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    sweep = [int(s) for s in args.sweep.split(",")]
    on_accel = dev.platform != "cpu"
    os.environ["BLUEFOG_BENCH_BATCH"] = str(args.batch)
    os.environ["BLUEFOG_BENCH_ITERS"] = str(args.iters)

    rows = []
    for i, spc in enumerate(sorted(sweep)):
        os.environ["BLUEFOG_BENCH_STEPS_PER_CALL"] = str(spc)
        tracing = args.trace and spc == max(sweep)
        if tracing:
            jax.profiler.start_trace(args.trace)
        r = bench.run_bench(on_accel, {"sweep_index": i})
        if tracing:
            jax.profiler.stop_trace()
        row = {"steps_per_call": spc, "imgs_per_sec_per_chip": r["value"],
               "mfu": r["mfu"]}
        rows.append(row)
        print(json.dumps(row), flush=True)

    base = rows[0]["imgs_per_sec_per_chip"]
    for row in rows:
        row["vs_spc1"] = round(row["imgs_per_sec_per_chip"] / base, 3)
    summary = {"device": dev.device_kind, "batch": args.batch,
               "rows": rows,
               "dispatch_amortization":
                   round(max(r["imgs_per_sec_per_chip"] for r in rows)
                         / base, 3)}
    print(json.dumps({"summary": summary["dispatch_amortization"],
                      "best": max(rows,
                                  key=lambda r: r["imgs_per_sec_per_chip"])}))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
        print(f"wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
