"""Strategy comparison: wire bytes (from compiled HLO) + step time.

One row per distributed-optimizer strategy on a fixed ~1M-param MLP: which
collectives the step compiles to, how many bytes each chip puts on the wire
per optimizer step (counted from the compiled program — ground truth, not
an analytic estimate), and the measured step time on the current backend.
Wire bytes come from an AOT compile against an abstract v5e topology when
libtpu is available (the TPU schedule is the one that matters: the CPU
backend's float normalization silently upcasts bf16 collectives, hiding
wire compression); otherwise the current backend's HLO.  The ms column is
the virtual CPU mesh unless run on real chips.  Counterpart of the
reference's published strategy table (``docs/performance.rst:26-53``).

Run: python tools/strategy_bench.py --virtual-cpu [--json]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# the counter lives in the library now (shared with the autotune cost
# model); re-exported here so `from strategy_bench import wire_stats`
# call sites keep working
from bluefog_tpu.utils.hlo_bytes import wire_stats  # noqa: E402,F401


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--virtual-cpu", action="store_true")
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--dim", type=int, default=512)
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args()

    if args.virtual_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    import jax
    if args.virtual_cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu import models, schedule as sch
    from bluefog_tpu import optimizers as bfopt
    from bluefog_tpu import topology as tu

    bf.init(platform="cpu" if args.virtual_cpu else None)
    n = bf.size()
    topo = tu.ExponentialTwoGraph(n)
    bf.set_topology(topo, is_weighted=True)
    dyn = sch.compile_dynamic_schedules(
        lambda r: tu.GetDynamicOnePeerSendRecvRanks(topo, r), n)

    D = args.dim
    model = models.MLP(features=(2 * D, D, 10))
    params = model.init(jax.random.key(0), jnp.ones((1, D)))
    p_count = sum(x.size for x in jax.tree.leaves(params))

    def grad_fn(p, batch):
        xb, yb = batch

        def loss(q):
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(q, xb), yb).mean()

        return jax.value_and_grad(loss)(p)

    opt = lambda: optax.sgd(0.05, momentum=0.9)
    strategies = {
        "allreduce": lambda: bfopt.gradient_allreduce(opt()),
        "neighbor (CTA)": lambda: bfopt.adapt_with_combine(
            opt(), bfopt.neighbor_communicator(bf.static_schedule())),
        "neighbor (ATC)": lambda: bfopt.adapt_then_combine(
            opt(), bfopt.neighbor_communicator(bf.static_schedule())),
        "dynamic one-peer": lambda: bfopt.adapt_with_combine(
            opt(), bfopt.neighbor_communicator(schedules=dyn)),
        "win_put": lambda: bfopt.win_put_optimizer(opt()),
        "push_sum": lambda: bfopt.push_sum(opt()),
        "zero-1 allreduce": lambda: bfopt.zero_gradient_allreduce(opt()),
        "choco (int8 wire)": lambda: bfopt.choco_gossip(opt()),
        "powersgd r=4": lambda: bfopt.powersgd_allreduce(
            opt(), compression_rank=4),
        "neighbor bf16 wire": lambda: bfopt.adapt_with_combine(
            opt(), bfopt.neighbor_communicator(bf.static_schedule(),
                                               wire="bf16")),
    }

    rng = np.random.default_rng(0)
    batch = (jnp.asarray(rng.normal(size=(n, 16, D)), jnp.float32),
             jnp.zeros((n, 16), jnp.int32))

    # abstract TPU target for the bytes column (the schedule that matters)
    tpu_mesh = None
    try:
        from jax.experimental import topologies
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        td = topologies.get_topology_desc("v5e:2x4", platform="tpu")
        if len(td.devices) == n:
            tpu_mesh = Mesh(np.array(td.devices), ("rank",))
        else:
            print(f"# TPU AOT target has {len(td.devices)} devices but "
                  f"n={n}; wire bytes are the current backend's HLO "
                  "(bf16 wire may show full width)", file=sys.stderr)
    except Exception as e:                              # noqa: BLE001
        print(f"# no TPU AOT target ({type(e).__name__}); wire bytes are "
              "the current backend's HLO", file=sys.stderr)

    def aot_wire(strategy, dist_params, dist_state):
        def per_rank(p, s, b):
            p, s, b = jax.tree.map(lambda t: t[0], (p, s, b))
            _, grads = grad_fn(p, b)
            new_p, new_s = strategy.update(grads, s, p)
            return jax.tree.map(lambda t: t[None], (new_p, new_s))

        fn = jax.jit(jax.shard_map(
            per_rank, mesh=tpu_mesh, in_specs=(P("rank"),) * 3,
            out_specs=(P("rank"),) * 2))
        sds = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=NamedSharding(tpu_mesh, P("rank"))),
            (dist_params, dist_state, batch))
        return wire_stats(fn.lower(*sds).compile().as_text())

    rows = []
    for name, make in strategies.items():
        strategy = make()
        dist_params = bfopt.replicate(params, n)
        dist_state = bfopt.init_distributed(strategy, dist_params)
        step = bfopt.make_train_step(grad_fn, strategy)
        compiled = step.lower(dist_params, dist_state, batch).compile()
        if tpu_mesh is not None:
            counts, bytes_ = aot_wire(strategy, dist_params, dist_state)
        else:
            counts, bytes_ = wire_stats(compiled.as_text())
        wire_mib = sum(bytes_.values()) / 2 ** 20
        fn = compiled
        ps, st, loss = fn(dist_params, dist_state, batch)
        bf.hard_sync(loss)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            ps, st, loss = fn(ps, st, batch)
        bf.hard_sync(loss)
        ms = (time.perf_counter() - t0) / args.iters * 1e3
        coll = ", ".join(f"{k.replace('collective-', '')}x{v}"
                         for k, v in sorted(counts.items())) or "none"
        rows.append((name, coll, wire_mib, ms))

    param_mib = p_count * 4 / 2 ** 20
    if args.json:
        import json
        for name, coll, mib, ms in rows:
            executed = mib / len(dyn) if "dynamic" in name else mib
            print(json.dumps({"strategy": name, "collectives": coll,
                              "wire_mib_per_step_per_chip": round(executed, 3),
                              "ms_per_step": round(ms, 2)}))
        return
    print(f"# {n} ranks, Exp2 topology, MLP {p_count:,} params "
          f"({param_mib:.1f} MiB f32), batch 16/rank")
    print(f"{'strategy':<20} {'collectives (per step)':<34} "
          f"{'wire MiB/chip':>13} {'ms/step':>9}")
    for name, coll, mib, ms in rows:
        note = ""
        if "dynamic" in name:
            # static HLO text carries every lax.switch branch; exactly one
            # permute round executes per step
            note = f"  († executes 1 of {len(dyn)} branches/step: "
            note += f"{mib / len(dyn):.2f} MiB)"
        print(f"{name:<20} {coll:<34} {mib:>13.2f} {ms:>9.2f}{note}")


if __name__ == "__main__":
    main()
