"""Merge per-process timeline files into one Chrome-tracing view.

The reference writes one ``${BLUEFOG_TIMELINE}<rank>.json`` per rank and
leaves merging to the user (``docs``); multi-process runs here likewise
produce one ``<prefix>.activities.json`` per process.  This tool stitches
them into a single trace with one process row per rank, so
chrome://tracing / Perfetto shows the whole cluster's activity alignment
(gossip spans lining up across ranks = the schedule is synchronous; gaps =
stragglers).

Usage: python tools/timeline_merge.py out.json rank0.activities.json \
           rank1.activities.json ...
"""
import json
import sys


def merge(paths):
    events = []
    for i, path in enumerate(paths):
        with open(path) as f:
            data = json.load(f)
        for ev in data.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = i            # one process row per input file
            events.append(ev)
        events.append({
            "name": "process_name", "ph": "M", "pid": i,
            "args": {"name": f"rank {i} ({path})"},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main():
    if len(sys.argv) < 3:
        raise SystemExit(__doc__)
    out, paths = sys.argv[1], sys.argv[2:]
    with open(out, "w") as f:
        json.dump(merge(paths), f)
    print(f"merged {len(paths)} timelines -> {out}")


if __name__ == "__main__":
    main()
