"""Hardware validation: run the Pallas kernels compiled on a real TPU chip.

The CI suite exercises the kernels in interpreter mode on the CPU virtual
mesh (tests/test_pallas_attention.py); this script is the complement — it
compiles the same kernels through Mosaic on the actual chip and checks them
against the dense-softmax oracle at hardware-realistic shapes, then times
them against the pure-XLA (jnp) formulation.

Run (needs the TPU tunnel, single client):  python tools/tpu_validate.py

Prints one JSON line per check: {"check", "ok", ...details}.

Isolation (default): each check group runs in its OWN subprocess with a
per-group timeout.  A remote Mosaic compile can wedge the axon tunnel
indefinitely (round 5: the whole script froze on its first kernel and
burned the battery step's full 3600 s); isolation converts that into one
lost group.  After any group timeout the parent re-probes the tunnel and
aborts the remaining groups if it stays unreachable — partial results
still land in ``--out``.  ``--inline`` restores the single-process mode.
"""
import argparse
import json
import os
import subprocess
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, ".")

RESULTS = []


def _load_heavy():
    """Import the jax stack only where it is used: the isolated-mode
    parent must stay un-wedgeable (and fast to start), so only the
    ``--inline`` children pay for — and risk — loading the axon-plugin-
    bearing jax stack and the kernel modules."""
    global jax, jnp, np, pa, hard_sync, enable_compilation_cache
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.api import hard_sync
    from bluefog_tpu.ops import pallas_attention as pa
    from bluefog_tpu.utils.config import enable_compilation_cache


def report(check, ok, **extra):
    line = {"check": check, "ok": bool(ok), **extra}
    RESULTS.append(line)
    print(json.dumps(line), flush=True)


def dense_oracle(q, k, v, causal, scale):
    s = np.einsum("bihd,bjhd->bihj", np.asarray(q, np.float64),
                  np.asarray(k, np.float64)) * scale
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        mask = np.arange(Tq)[:, None] >= np.arange(Tk)[None, :]
        s = np.where(mask[None, :, None, :], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    return np.einsum("bihj,bjhd->bihd", p / p.sum(-1, keepdims=True),
                     np.asarray(v, np.float64))


def check_forward(B, T, H, D, causal, block_q, tag):
    rng = np.random.default_rng(0)
    scale = 1.0 / np.sqrt(D)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
               for _ in range(3))
    o, l, m = pa.attention_block_partial(
        q, k, v, jnp.asarray(0), jnp.asarray(0),
        causal=causal, scale=scale, interpret=False, block_q=block_q)
    out = np.asarray(o) / np.asarray(l)[..., None]
    expected = dense_oracle(q, k, v, causal, scale)
    err = float(np.max(np.abs(out - expected)))
    report(f"pallas_fwd_{tag}", err < 1e-4, max_abs_err=err,
           shape=[B, T, H, D], causal=causal, block_q=block_q)


def check_backward(B, T, H, D, causal, block_q, tag):
    rng = np.random.default_rng(1)
    scale = 1.0 / np.sqrt(D)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
               for _ in range(3))

    def loss(q_, k_, v_):
        s = jnp.einsum("bihd,bjhd->bihj", q_, k_) * scale
        if causal:
            mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
            s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bihj,bjhd->bihd", p, v_)
        return jnp.sum(out ** 2), out

    (_, out), (dq_e, dk_e, dv_e) = jax.value_and_grad(
        loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    do = 2.0 * out

    _, l, m = pa.attention_block_partial(
        q, k, v, jnp.asarray(0), jnp.asarray(0), causal=causal,
        scale=scale, interpret=False, block_q=block_q)
    lse = jnp.where(l == 0.0, -jnp.inf, m + jnp.log(jnp.where(l == 0, 1, l)))
    delta = jnp.sum(do * out, axis=-1)
    dq, dk, dv = pa.attention_block_backward(
        q, k, v, do, lse, delta, jnp.asarray(0), jnp.asarray(0),
        causal=causal, scale=scale, interpret=False, block_q=block_q)

    errs = {n: float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for n, a, b in (("dq", dq, dq_e), ("dk", dk, dk_e),
                            ("dv", dv, dv_e))}
    scale_ref = max(float(np.max(np.abs(np.asarray(g))))
                    for g in (dq_e, dk_e, dv_e))
    ok = all(e < 1e-3 * max(scale_ref, 1.0) for e in errs.values())
    report(f"pallas_bwd_{tag}", ok, errors=errs,
           shape=[B, T, H, D], causal=causal, block_q=block_q)


def time_fn(fn, *args, iters=20):
    out = fn(*args)
    hard_sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    hard_sync(out)
    return (time.perf_counter() - t0) / iters


def bench_kernel(B, T, H, D, block_q):
    """Pallas partial vs the pure-jnp formulation of the same partial."""
    rng = np.random.default_rng(2)
    scale = 1.0 / np.sqrt(D)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.bfloat16)
               for _ in range(3))

    def pallas_fn(q, k, v):
        return pa.attention_block_partial(
            q, k, v, jnp.asarray(0), jnp.asarray(0),
            causal=True, scale=scale, interpret=False, block_q=block_q)

    @jax.jit
    def jnp_fn(q, k, v):
        s = jnp.einsum("bihd,bjhd->bihj", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        s = jnp.where(mask[None, :, None, :], s, pa.NEG_INF)
        m = jnp.max(s, axis=-1)
        p = jnp.exp(s - m[..., None])
        l = jnp.sum(p, axis=-1)
        o = jnp.einsum("bihj,bjhd->bihd", p, v.astype(jnp.float32))
        return o, l, m

    t_pallas = time_fn(pallas_fn, q, k, v)
    t_jnp = time_fn(jnp_fn, q, k, v)
    # causal partial: ~half the full 4*B*H*T^2*D matmul flops
    flops = 2 * 2 * B * H * T * T * D
    report("pallas_vs_jnp_timing", t_pallas <= t_jnp * 1.5,
           shape=[B, T, H, D], block_q=block_q,
           pallas_ms=round(t_pallas * 1e3, 3), jnp_ms=round(t_jnp * 1e3, 3),
           speedup=round(t_jnp / t_pallas, 2),
           pallas_tflops=round(flops / t_pallas / 1e12, 2))


def check_ring_single_device():
    """ring_attention with use_pallas on a 1-chip mesh: fwd + grads, plus
    the GQA (compact kv) and zigzag-layout paths vs the dense oracle."""
    from jax.sharding import PartitionSpec as P
    import bluefog_tpu as bf
    from bluefog_tpu.ops import ring_attention, zigzag_order, zigzag_inverse

    bf.init()
    try:
        rng = np.random.default_rng(3)
        B, T, H, D = 1, 512, 4, 64
        q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
                   for _ in range(3))

        def loss(qb, kb, vb):
            out = ring_attention(qb, kb, vb, axis="rank", causal=True,
                                 use_pallas=True)
            return jax.lax.psum(jnp.sum(out ** 2), "rank"), out

        g = jax.value_and_grad(loss, argnums=(0, 1, 2), has_aux=True)
        # check_vma=False: the pallas kernel's scalar chunk offsets are
        # unvarying beside rank-varying blocks (known jax VMA false positive;
        # same workaround as tests/test_ring.py)
        fn = jax.jit(jax.shard_map(
            g, mesh=bf.mesh(), in_specs=(P(None, "rank"),) * 3,
            out_specs=((P(), P(None, "rank")), (P(None, "rank"),) * 3),
            check_vma=False))
        (_, out), grads = fn(q, k, v)
        expected = dense_oracle(q, k, v, True, 1.0 / np.sqrt(D))
        err = float(np.max(np.abs(np.asarray(out) - expected)))
        finite = all(bool(np.all(np.isfinite(np.asarray(x)))) for x in grads)
        report("ring_attention_pallas_1chip", err < 1e-4 and finite,
               max_abs_err=err, grads_finite=finite, shape=[B, T, H, D])

        # GQA: 8 q heads sharing 2 kv heads (4x fewer ring bytes); oracle is
        # dense attention with the kv heads repeated per group
        Hq, Hkv = 8, 2
        qg = jnp.asarray(rng.normal(size=(B, T, Hq, D)), jnp.float32)
        kg, vg = (jnp.asarray(rng.normal(size=(B, T, Hkv, D)), jnp.float32)
                  for _ in range(2))
        gqa_fn = jax.jit(jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis="rank", causal=True,
                                           use_pallas=True),
            mesh=bf.mesh(), in_specs=(P(None, "rank"),) * 3,
            out_specs=P(None, "rank"), check_vma=False))
        out_g = gqa_fn(qg, kg, vg)
        rep = Hq // Hkv
        exp_g = dense_oracle(qg, np.repeat(np.asarray(kg), rep, axis=2),
                             np.repeat(np.asarray(vg), rep, axis=2),
                             True, 1.0 / np.sqrt(D))
        err_g = float(np.max(np.abs(np.asarray(out_g) - exp_g)))
        report("ring_attention_pallas_gqa", err_g < 1e-4, max_abs_err=err_g,
               q_heads=Hq, kv_heads=Hkv)

        # zigzag (balanced causal) layout through the Pallas path: feed the
        # zigzag-permuted sequence, un-permute, compare to the dense oracle
        n = bf.size()
        order = zigzag_order(n, T)
        inv = zigzag_inverse(n, T)
        zz_fn = jax.jit(jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis="rank", causal=True,
                                           layout="zigzag", use_pallas=True),
            mesh=bf.mesh(), in_specs=(P(None, "rank"),) * 3,
            out_specs=P(None, "rank"), check_vma=False))
        out_z = np.asarray(zz_fn(q[:, order], k[:, order], v[:, order]))
        err_z = float(np.max(np.abs(out_z[:, inv] - expected)))
        report("ring_attention_pallas_zigzag", err_z < 1e-4,
               max_abs_err=err_z, shape=[B, T, H, D])
    finally:
        bf.shutdown()


# MXU-aligned shapes; 768 exercises the q-block padding path (advisor fix)
GROUPS = {
    "fwd_1k": lambda: check_forward(2, 1024, 4, 128, causal=True,
                                    block_q=512, tag="1k_causal"),
    "fwd_768": lambda: check_forward(2, 768, 4, 128, causal=False,
                                     block_q=512, tag="768_pad"),
    "bwd_512": lambda: check_backward(1, 512, 4, 128, causal=True,
                                      block_q=256, tag="512_causal"),
    "bwd_384": lambda: check_backward(1, 384, 2, 64, causal=False,
                                      block_q=256, tag="384_pad"),
    "timing": lambda: bench_kernel(4, 2048, 8, 128, block_q=512),
    "ring": check_ring_single_device,
}


def _cpu_pinned() -> bool:
    return os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"


def _run_groups_inline(names) -> str:
    """Dial the accelerator and run the named groups in THIS process.
    Returns the device kind (exits 2 when only a CPU is available)."""
    _load_heavy()
    # honor an explicit CPU pin: the axon plugin force-overrides the
    # JAX_PLATFORMS env var at boot, so without this a CPU-pinned run
    # (battery rehearsal, CI) dials the TPU tunnel just to refuse
    if _cpu_pinned():
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        print("refusing: no accelerator", file=sys.stderr)
        sys.exit(2)
    enable_compilation_cache()
    report("device", True, kind=dev.device_kind, platform=dev.platform)
    for n in names:
        GROUPS[n]()
    return dev.device_kind


def _probe_alive(timeout_s: float) -> bool:
    """Re-probe the tunnel from a fresh subprocess (bench._probe owns the
    probe command + kill loop); records the outcome in the shared state
    file so a dead tunnel also shortens later bench.py probing."""
    import bench as _bench
    t0 = time.monotonic()
    ok = _bench._probe(dict(os.environ), timeout_s)
    _bench.write_probe_state(ok, time.monotonic() - t0,
                             writer="tpu_validate")
    return ok


def _write_out(out_path, device) -> None:
    """Persist whatever has landed so far: an outer kill (the battery's
    step timeout) must not erase completed groups' results."""
    if not out_path:
        return
    ok = all(r["ok"] for r in RESULTS)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump({"device": device, "results": RESULTS,
                   "summary": "PASS" if ok else "FAIL",
                   "n_checks": len(RESULTS)}, f, indent=1)


def _run_isolated(args, names) -> str:
    """One subprocess per group, each under ``--group-timeout`` and a
    total ``--budget``; after a timeout, settle + re-probe before dialing
    again (a killed client can leave the single-client axon relay
    jammed).  ``--out`` is rewritten after every group."""
    device = "unknown"
    device_reported = False
    start = time.monotonic()
    pending = list(names)
    # a wedged group costs settle + probe on top of its timeout; reserve
    # that headroom so the WHOLE worst case stays inside --budget (which
    # in turn sits under the caller's step timeout — partial results must
    # be written by this process, not lost to an outer kill)
    recovery = args.settle_s + args.probe_timeout
    while pending:
        name = pending.pop(0)
        usable = (args.budget - (time.monotonic() - start)
                  - (recovery if pending else 0.0))
        if usable < 60.0:
            report(f"group_{name}", False, error="skipped: budget exhausted")
            continue
        argv = [sys.executable, os.path.abspath(__file__), "--inline",
                "--only", name]
        t0 = time.monotonic()
        # children share this process group on purpose: an outer killpg
        # aimed at this parent (hw_watch's battery-step timeout) must take
        # the in-flight tunnel dialer down with it.  Groups spawn no
        # grandchildren, so p.kill() suffices for the per-group timeout.
        p = subprocess.Popen(argv, cwd=os.path.dirname(_HERE), text=True,
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        try:
            out, err = p.communicate(
                timeout=min(args.group_timeout, usable))
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            report(f"group_{name}", False, error="timeout",
                   seconds=round(time.monotonic() - t0, 1))
            _write_out(args.out, device)
            if pending:
                print(f"validate: group '{name}' wedged; settling "
                      f"{args.settle_s:.0f}s then re-probing the tunnel",
                      file=sys.stderr, flush=True)
                time.sleep(args.settle_s)
                if not _probe_alive(args.probe_timeout):
                    for rest in pending:
                        report(f"group_{rest}", False,
                               error="skipped: tunnel unreachable")
                    pending = []
            continue
        if err.strip():
            sys.stderr.write(err)
        for ln in out.splitlines():
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if rec.get("check") == "device":
                device = rec.get("kind", device)
                if not device_reported:       # echo the device line once
                    device_reported = True
                    RESULTS.append(rec)
                    print(json.dumps(rec), flush=True)
            elif "check" in rec:
                RESULTS.append(rec)
                print(json.dumps(rec), flush=True)
        if p.returncode == 2:
            if not RESULTS:
                # nothing ran yet and there is no accelerator: refuse
                # like the inline mode (any prior record — even a
                # timeout — means the tunnel WAS being dialed, so fall
                # through to the vanished-mid-run branch instead)
                print("refusing: no accelerator", file=sys.stderr)
                sys.exit(2)
            # the tunnel served earlier groups but now exposes no TPU
            # (daemon restart): keep the banked results, record the loss
            report(f"group_{name}", False,
                   error="accelerator vanished mid-run (exit 2)")
        elif p.returncode not in (0, 1):    # crash without JSON output
            report(f"group_{name}", False,
                   error=f"exit {p.returncode}",
                   seconds=round(time.monotonic() - t0, 1))
        _write_out(args.out, device)
    return device


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--inline", action="store_true",
                    help="single-process mode (no per-group isolation)")
    ap.add_argument("--only", action="append", choices=sorted(GROUPS),
                    help="run only these groups (repeatable)")
    ap.add_argument("--group-timeout", type=float, default=900.0,
                    help="per-group subprocess timeout (isolated mode)")
    ap.add_argument("--budget", type=float, default=2700.0,
                    help="total wall-clock budget for all groups; must sit "
                         "under the caller's own step timeout so partial "
                         "results are written by THIS process, not lost "
                         "to an outer kill")
    ap.add_argument("--settle-s", type=float, default=150.0,
                    help="quiet period after a wedged group before the "
                         "re-probe dials the relay again")
    ap.add_argument("--probe-timeout", type=float, default=150.0)
    args = ap.parse_args()

    names = args.only or list(GROUPS)
    if args.inline:
        device = _run_groups_inline(names)
    else:
        if _cpu_pinned():                   # refuse without spawning
            print("refusing: no accelerator", file=sys.stderr)
            sys.exit(2)
        device = _run_isolated(args, names)

    ok = all(r["ok"] for r in RESULTS)
    print(json.dumps({"summary": "PASS" if ok else "FAIL",
                      "n_checks": len(RESULTS)}))
    _write_out(args.out, device)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
