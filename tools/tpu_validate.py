"""Hardware validation: run the Pallas kernels compiled on a real TPU chip.

The CI suite exercises the kernels in interpreter mode on the CPU virtual
mesh (tests/test_pallas_attention.py); this script is the complement — it
compiles the same kernels through Mosaic on the actual chip and checks them
against the dense-softmax oracle at hardware-realistic shapes, then times
them against the pure-XLA (jnp) formulation.

Run (needs the TPU tunnel, single client):  python tools/tpu_validate.py

Prints one JSON line per check: {"check", "ok", ...details}.
"""
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from bluefog_tpu.api import hard_sync  # noqa: E402
from bluefog_tpu.ops import pallas_attention as pa  # noqa: E402
from bluefog_tpu.utils.config import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

RESULTS = []


def report(check, ok, **extra):
    line = {"check": check, "ok": bool(ok), **extra}
    RESULTS.append(line)
    print(json.dumps(line), flush=True)


def dense_oracle(q, k, v, causal, scale):
    s = np.einsum("bihd,bjhd->bihj", np.asarray(q, np.float64),
                  np.asarray(k, np.float64)) * scale
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        mask = np.arange(Tq)[:, None] >= np.arange(Tk)[None, :]
        s = np.where(mask[None, :, None, :], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    return np.einsum("bihj,bjhd->bihd", p / p.sum(-1, keepdims=True),
                     np.asarray(v, np.float64))


def check_forward(B, T, H, D, causal, block_q, tag):
    rng = np.random.default_rng(0)
    scale = 1.0 / np.sqrt(D)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
               for _ in range(3))
    o, l, m = pa.attention_block_partial(
        q, k, v, jnp.asarray(0), jnp.asarray(0),
        causal=causal, scale=scale, interpret=False, block_q=block_q)
    out = np.asarray(o) / np.asarray(l)[..., None]
    expected = dense_oracle(q, k, v, causal, scale)
    err = float(np.max(np.abs(out - expected)))
    report(f"pallas_fwd_{tag}", err < 1e-4, max_abs_err=err,
           shape=[B, T, H, D], causal=causal, block_q=block_q)


def check_backward(B, T, H, D, causal, block_q, tag):
    rng = np.random.default_rng(1)
    scale = 1.0 / np.sqrt(D)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
               for _ in range(3))

    def loss(q_, k_, v_):
        s = jnp.einsum("bihd,bjhd->bihj", q_, k_) * scale
        if causal:
            mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
            s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bihj,bjhd->bihd", p, v_)
        return jnp.sum(out ** 2), out

    (_, out), (dq_e, dk_e, dv_e) = jax.value_and_grad(
        loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    do = 2.0 * out

    _, l, m = pa.attention_block_partial(
        q, k, v, jnp.asarray(0), jnp.asarray(0), causal=causal,
        scale=scale, interpret=False, block_q=block_q)
    lse = jnp.where(l == 0.0, -jnp.inf, m + jnp.log(jnp.where(l == 0, 1, l)))
    delta = jnp.sum(do * out, axis=-1)
    dq, dk, dv = pa.attention_block_backward(
        q, k, v, do, lse, delta, jnp.asarray(0), jnp.asarray(0),
        causal=causal, scale=scale, interpret=False, block_q=block_q)

    errs = {n: float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for n, a, b in (("dq", dq, dq_e), ("dk", dk, dk_e),
                            ("dv", dv, dv_e))}
    scale_ref = max(float(np.max(np.abs(np.asarray(g))))
                    for g in (dq_e, dk_e, dv_e))
    ok = all(e < 1e-3 * max(scale_ref, 1.0) for e in errs.values())
    report(f"pallas_bwd_{tag}", ok, errors=errs,
           shape=[B, T, H, D], causal=causal, block_q=block_q)


def time_fn(fn, *args, iters=20):
    out = fn(*args)
    hard_sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    hard_sync(out)
    return (time.perf_counter() - t0) / iters


def bench_kernel(B, T, H, D, block_q):
    """Pallas partial vs the pure-jnp formulation of the same partial."""
    rng = np.random.default_rng(2)
    scale = 1.0 / np.sqrt(D)
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.bfloat16)
               for _ in range(3))

    def pallas_fn(q, k, v):
        return pa.attention_block_partial(
            q, k, v, jnp.asarray(0), jnp.asarray(0),
            causal=True, scale=scale, interpret=False, block_q=block_q)

    @jax.jit
    def jnp_fn(q, k, v):
        s = jnp.einsum("bihd,bjhd->bihj", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        s = jnp.where(mask[None, :, None, :], s, pa.NEG_INF)
        m = jnp.max(s, axis=-1)
        p = jnp.exp(s - m[..., None])
        l = jnp.sum(p, axis=-1)
        o = jnp.einsum("bihj,bjhd->bihd", p, v.astype(jnp.float32))
        return o, l, m

    t_pallas = time_fn(pallas_fn, q, k, v)
    t_jnp = time_fn(jnp_fn, q, k, v)
    # causal partial: ~half the full 4*B*H*T^2*D matmul flops
    flops = 2 * 2 * B * H * T * T * D
    report("pallas_vs_jnp_timing", t_pallas <= t_jnp * 1.5,
           shape=[B, T, H, D], block_q=block_q,
           pallas_ms=round(t_pallas * 1e3, 3), jnp_ms=round(t_jnp * 1e3, 3),
           speedup=round(t_jnp / t_pallas, 2),
           pallas_tflops=round(flops / t_pallas / 1e12, 2))


def check_ring_single_device():
    """ring_attention with use_pallas on a 1-chip mesh: fwd + grads, plus
    the GQA (compact kv) and zigzag-layout paths vs the dense oracle."""
    from jax.sharding import PartitionSpec as P
    import bluefog_tpu as bf
    from bluefog_tpu.ops import ring_attention, zigzag_order, zigzag_inverse

    bf.init()
    try:
        rng = np.random.default_rng(3)
        B, T, H, D = 1, 512, 4, 64
        q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
                   for _ in range(3))

        def loss(qb, kb, vb):
            out = ring_attention(qb, kb, vb, axis="rank", causal=True,
                                 use_pallas=True)
            return jax.lax.psum(jnp.sum(out ** 2), "rank"), out

        g = jax.value_and_grad(loss, argnums=(0, 1, 2), has_aux=True)
        # check_vma=False: the pallas kernel's scalar chunk offsets are
        # unvarying beside rank-varying blocks (known jax VMA false positive;
        # same workaround as tests/test_ring.py)
        fn = jax.jit(jax.shard_map(
            g, mesh=bf.mesh(), in_specs=(P(None, "rank"),) * 3,
            out_specs=((P(), P(None, "rank")), (P(None, "rank"),) * 3),
            check_vma=False))
        (_, out), grads = fn(q, k, v)
        expected = dense_oracle(q, k, v, True, 1.0 / np.sqrt(D))
        err = float(np.max(np.abs(np.asarray(out) - expected)))
        finite = all(bool(np.all(np.isfinite(np.asarray(x)))) for x in grads)
        report("ring_attention_pallas_1chip", err < 1e-4 and finite,
               max_abs_err=err, grads_finite=finite, shape=[B, T, H, D])

        # GQA: 8 q heads sharing 2 kv heads (4x fewer ring bytes); oracle is
        # dense attention with the kv heads repeated per group
        Hq, Hkv = 8, 2
        qg = jnp.asarray(rng.normal(size=(B, T, Hq, D)), jnp.float32)
        kg, vg = (jnp.asarray(rng.normal(size=(B, T, Hkv, D)), jnp.float32)
                  for _ in range(2))
        gqa_fn = jax.jit(jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis="rank", causal=True,
                                           use_pallas=True),
            mesh=bf.mesh(), in_specs=(P(None, "rank"),) * 3,
            out_specs=P(None, "rank"), check_vma=False))
        out_g = gqa_fn(qg, kg, vg)
        rep = Hq // Hkv
        exp_g = dense_oracle(qg, np.repeat(np.asarray(kg), rep, axis=2),
                             np.repeat(np.asarray(vg), rep, axis=2),
                             True, 1.0 / np.sqrt(D))
        err_g = float(np.max(np.abs(np.asarray(out_g) - exp_g)))
        report("ring_attention_pallas_gqa", err_g < 1e-4, max_abs_err=err_g,
               q_heads=Hq, kv_heads=Hkv)

        # zigzag (balanced causal) layout through the Pallas path: feed the
        # zigzag-permuted sequence, un-permute, compare to the dense oracle
        n = bf.size()
        order = zigzag_order(n, T)
        inv = zigzag_inverse(n, T)
        zz_fn = jax.jit(jax.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis="rank", causal=True,
                                           layout="zigzag", use_pallas=True),
            mesh=bf.mesh(), in_specs=(P(None, "rank"),) * 3,
            out_specs=P(None, "rank"), check_vma=False))
        out_z = np.asarray(zz_fn(q[:, order], k[:, order], v[:, order]))
        err_z = float(np.max(np.abs(out_z[:, inv] - expected)))
        report("ring_attention_pallas_zigzag", err_z < 1e-4,
               max_abs_err=err_z, shape=[B, T, H, D])
    finally:
        bf.shutdown()


def main():
    out_path = None
    for i, a in enumerate(sys.argv):
        if a == "--out" and i + 1 < len(sys.argv):
            out_path = sys.argv[i + 1]

    # honor an explicit CPU pin: the axon plugin force-overrides the
    # JAX_PLATFORMS env var at boot, so without this a CPU-pinned run
    # (battery rehearsal, CI) dials the TPU tunnel just to refuse
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    if dev.platform == "cpu":
        print("refusing: no accelerator", file=sys.stderr)
        sys.exit(2)
    report("device", True, kind=dev.device_kind, platform=dev.platform)

    # MXU-aligned shapes; 768 exercises the q-block padding path (advisor fix)
    check_forward(2, 1024, 4, 128, causal=True, block_q=512, tag="1k_causal")
    check_forward(2, 768, 4, 128, causal=False, block_q=512, tag="768_pad")
    check_backward(1, 512, 4, 128, causal=True, block_q=256, tag="512_causal")
    check_backward(1, 384, 2, 64, causal=False, block_q=256, tag="384_pad")
    bench_kernel(4, 2048, 8, 128, block_q=512)
    check_ring_single_device()

    ok = all(r["ok"] for r in RESULTS)
    summary = {"summary": "PASS" if ok else "FAIL", "n_checks": len(RESULTS)}
    print(json.dumps(summary))
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as f:
            json.dump({"device": dev.device_kind, "results": RESULTS,
                       **summary}, f, indent=1)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
