"""Step-time decomposition from a jax.profiler trace (round-5 verdict #2).

Parses the Chrome-trace JSON that ``jax.profiler.trace`` (invoked by
``tools/step_sweep.py --trace``) writes, and attributes device time to
COMPUTE vs COMM, measuring how much communication is EXPOSED (not
overlapped by compute).  This is the trace-derived evidence behind the
overlap story: the reference's >=95% scaling claim
(``README.rst:26-34``) rests on gossip permutes hiding behind backward
compute, and the same must hold for the XLA async-collective schedule
this framework relies on (``docs/PERFORMANCE.md`` "overlap proof").

Method: take the device track(s) (process names matching TPU/device;
fallback: the busiest track), classify complete events by op name
(collective ops vs everything else), merge each class into disjoint
intervals, and measure comm time not covered by compute intervals.
Reported numbers:

    wall_ms            last device event end - first start
    compute_ms         union of compute intervals
    comm_ms            union of comm intervals
    comm_exposed_ms    comm intervals minus compute coverage
    overlap_fraction   1 - exposed/comm (1.0 = fully hidden)
    idle_ms            wall - union(all device intervals) — dispatch gaps
    top_exposed_comm_ops  per-op attribution of the exposed time: comm
                       events grouped by canonical op name (trailing
                       ``.N`` instance suffix stripped), each group's
                       intervals measured against the compute cover,
                       top-k by exposed ms — so a regression names the
                       offending collective instead of an aggregate

Run: python tools/trace_analyze.py <trace_dir_or_file> [--out out.json]
"""
import argparse
import glob
import gzip
import json
import os
import re
import sys

# Collective classifier.  Substring match over the comm-op token set,
# tolerant of the spellings XLA traces actually contain: dashed HLO names
# ("all-reduce.3"), underscore/camel-case metadata ("AllToAll"), ragged
# variants ("ragged-all-to-all.1"), and async pairs — including
# fusion-wrapped ones like "loop_fusion.collective-permute-start.5" —
# whose -start/-done halves must both count as comm.  "copy-start"/"copy-
# done" (async D2D copies) must NOT match: no comm token, no match.
COMM_RE = re.compile(
    r"ragged[-_]?all[-_]?to[-_]?all"
    r"|all[-_]?reduce|all[-_]?gather|reduce[-_]?scatter"
    r"|collective[-_]?permute|all[-_]?to[-_]?all|collective[-_]?broadcast"
    r"|\bsend(?:[-_]done)?\b|\brecv(?:[-_]done)?\b"
    r"|ppermute|collective", re.I)
DEVICE_RE = re.compile(r"tpu|/device:|gpu", re.I)


def find_trace_file(path):
    if os.path.isfile(path):
        return path
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        hits = sorted(glob.glob(os.path.join(path, pat), recursive=True))
        if hits:
            return hits[-1]                  # newest run dir sorts last
    raise FileNotFoundError(f"no *.trace.json[.gz] under {path}")


def load_events(trace_file):
    opener = gzip.open if trace_file.endswith(".gz") else open
    with opener(trace_file, "rt") as f:
        doc = json.load(f)
    return doc.get("traceEvents", doc if isinstance(doc, list) else [])


def merge(intervals):
    """Union of [start, end) intervals; returns merged list + total."""
    out = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out, sum(e - s for s, e in out)


def subtract(base, cover):
    """Total length of ``base`` intervals not covered by ``cover``."""
    total = 0.0
    ci = 0
    for s, e in base:
        pos = s
        while pos < e:
            while ci < len(cover) and cover[ci][1] <= pos:
                ci += 1
            if ci >= len(cover) or cover[ci][0] >= e:
                total += e - pos
                break
            c0, c1 = cover[ci]
            if c0 > pos:
                total += c0 - pos
            pos = c1
    return total


_INSTANCE_RE = re.compile(r"(\.\d+)+$")


def canonical_op(name):
    """Collapse per-instance HLO names: ``collective-permute-start.5`` and
    ``collective-permute-start.12`` are the same op for attribution."""
    return _INSTANCE_RE.sub("", name or "")


def top_exposed_comm_ops(comm_events, comp_cover, k=5):
    """Per-op exposed time: group comm events by canonical name, measure
    each group's merged intervals against the compute cover.  Returns the
    top-k groups by exposed ms (ties broken by total ms), each as
    ``{"name", "count", "total_ms", "exposed_ms"}``."""
    by_name = {}
    for ev in comm_events:
        iv = (float(ev["ts"]), float(ev["ts"]) + float(ev["dur"]))
        by_name.setdefault(canonical_op(ev.get("name", "")), []).append(iv)
    us = 1e-3
    rows = []
    for name, ivs in by_name.items():
        merged, total = merge(ivs)
        rows.append({
            "name": name,
            "count": len(ivs),
            "total_ms": round(total * us, 3),
            "exposed_ms": round(subtract(merged, comp_cover) * us, 3),
        })
    rows.sort(key=lambda r: (-r["exposed_ms"], -r["total_ms"], r["name"]))
    return rows[:k]


def analyze(events):
    pid_names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev.get("pid")] = ev.get("args", {}).get("name", "")
    device_pids = {p for p, n in pid_names.items() if DEVICE_RE.search(n)}
    xs = [ev for ev in events
          if ev.get("ph") == "X" and ev.get("dur", 0) > 0]
    if device_pids:
        xs = [ev for ev in xs if ev.get("pid") in device_pids]
    elif xs:
        # fallback: the busiest pid is the device/op track
        busy = {}
        for ev in xs:
            busy[ev.get("pid")] = busy.get(ev.get("pid"), 0) + ev["dur"]
        top = max(busy, key=busy.get)
        xs = [ev for ev in xs if ev.get("pid") == top]
    if not xs:
        return {"ok": False, "error": "no complete events on device tracks"}

    comm_iv, comp_iv, comm_events = [], [], []
    for ev in xs:
        iv = (float(ev["ts"]), float(ev["ts"]) + float(ev["dur"]))
        if COMM_RE.search(ev.get("name", "")):
            comm_iv.append(iv)
            comm_events.append(ev)
        else:
            comp_iv.append(iv)
    comm_m, comm_total = merge(comm_iv)
    comp_m, comp_total = merge(comp_iv)
    all_m, busy_total = merge(comm_iv + comp_iv)
    wall = max(e for _, e in all_m) - min(s for s, _ in all_m)
    exposed = subtract(comm_m, comp_m)
    us = 1e-3                                 # trace timestamps are in us
    return {
        "ok": True,
        "n_events": len(xs),
        "wall_ms": round(wall * us, 3),
        "busy_ms": round(busy_total * us, 3),
        "compute_ms": round(comp_total * us, 3),
        "comm_ms": round(comm_total * us, 3),
        "comm_exposed_ms": round(exposed * us, 3),
        "overlap_fraction": (round(1.0 - exposed / comm_total, 4)
                             if comm_total > 0 else None),
        "idle_ms": round((wall - busy_total) * us, 3),
        "top_exposed_comm_ops": top_exposed_comm_ops(comm_events, comp_m),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", help="trace dir (or .trace.json[.gz] file)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    try:
        tf = find_trace_file(args.trace)
        doc = analyze(load_events(tf))
        doc["trace_file"] = tf
    except (OSError, ValueError, FileNotFoundError) as e:
        doc = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(doc))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    sys.exit(0 if doc.get("ok") else 1)


if __name__ == "__main__":
    main()
