"""Merge per-rank trace bundles into Chrome-trace + critical-path report.

Each rank armed with ``BLUEFOG_TRACE=<dir>`` writes
``<dir>/trace_rank<r>.trace.jsonl`` (schema ``bluefog-trace-1``: one
``meta`` line carrying a ``(monotonic, wall)`` clock anchor, then one
line per span — see ``bluefog_tpu/utils/tracing.py``).  This tool is the
job-level view:

* **merge** — every bundle's spans on one wall-clock axis (span
  endpoints are per-rank ``time.monotonic()``; the meta anchor converts
  them: ``wall = meta.wall + (t - meta.mono)``),
* **--chrome** — a ``chrome://tracing`` / Perfetto file (``traceEvents``
  with ``ph: "X"`` complete events, one process per rank, one thread
  lane per trace id),
* **critical path** — per-request breakdown from the ``cat="serve"``
  span tree: queue wait vs prefill vs summed fused-decode time vs the
  scheduling gap (host time between calls).  The root ``request`` span's
  endpoints are the scheduler's own ``submitted_at``/``finished_at``
  stamps, so ``total_s`` IS the request's measured E2E latency and
  ``queue + prefill + decode + gap == total`` by construction.

Run: python tools/trace_report.py <bundle.trace.jsonl> ... [--dir DIR]
     [--out report.json] [--chrome trace.json]

Output schema (stable, pinned by tests/test_tracing.py):
    {"ok": bool, "schema": "bluefog-trace-report-1",
     "n_ranks": int, "ranks": [...], "n_spans": int, "dropped": int,
     "requests": {trace_id: {"total_s", "queue_s", "prefill_s",
                             "decode_s", "gap_s", "n_decode_calls",
                             "tokens", "replica", "prefix_hit",
                             "spec_accepted"}},
     "critical_path": [[trace_id, total_s, queue_s, prefill_s, decode_s,
                        gap_s], ...]   # slowest first
     "train": {"steps": int, "step_mean_s": float|None,
               "probes": int}}
"""
import argparse
import glob
import json
import os
import sys
import time

SCHEMA = "bluefog-trace-report-1"
BUNDLE_SCHEMA = "bluefog-trace-1"


def load_bundle(path, notes=None):
    """One bundle -> (meta, [spans]).  Torn trailing lines (the writer
    died mid-append) are skipped with a warning, never fatal."""
    meta, spans = None, []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError as e:
                msg = (f"warning: {path}:{lineno}: skipping torn JSONL "
                       f"line ({e.msg})")
                print(msg, file=sys.stderr)
                if notes is not None:
                    notes.append(msg)
                continue
            if doc.get("kind") == "meta":
                meta = doc
            elif doc.get("kind") == "span":
                spans.append(doc)
    if meta is None:
        raise ValueError(f"{path}: no meta line (not a {BUNDLE_SCHEMA} "
                         "bundle?)")
    if meta.get("schema") != BUNDLE_SCHEMA:
        raise ValueError(f"{path}: schema {meta.get('schema')!r} != "
                         f"{BUNDLE_SCHEMA!r}")
    return meta, spans


def _wall(meta, t):
    """Per-rank monotonic timestamp -> shared wall-clock seconds."""
    return meta["wall"] + (t - meta["mono"])


ATTR_SKIP = {"kind", "seq", "trace", "span", "name", "t0", "t1", "cat",
             "parent"}


def chrome_trace(bundles):
    """``[(meta, spans)]`` -> Chrome-trace dict (``traceEvents``).

    One pid per rank, one tid lane per trace id within the rank; ts/dur
    in microseconds relative to the earliest span across all ranks.
    """
    t_min = None
    for meta, spans in bundles:
        for s in spans:
            w = _wall(meta, s["t0"])
            t_min = w if t_min is None or w < t_min else t_min
    events = []
    for meta, spans in bundles:
        rank = meta.get("rank", 0)
        events.append({"ph": "M", "pid": rank, "tid": 0,
                       "name": "process_name",
                       "args": {"name": f"rank{rank}"}})
        lanes = {}
        for s in spans:
            trace = s.get("trace", "")
            tid = lanes.get(trace)
            if tid is None:
                tid = lanes[trace] = len(lanes) + 1
                events.append({"ph": "M", "pid": rank, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": trace}})
            w0 = _wall(meta, s["t0"])
            dur = max(s["t1"] - s["t0"], 0.0)
            events.append({
                "ph": "X", "pid": rank, "tid": tid,
                "name": s.get("name", "?"), "cat": s.get("cat") or "span",
                "ts": round((w0 - (t_min or 0.0)) * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "args": {k: v for k, v in s.items() if k not in ATTR_SKIP},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def critical_path(bundles):
    """Per-request breakdown from the serve span trees.

    Only requests with a root ``request`` span (i.e. retired) get a row.
    ``gap_s`` is everything the named child spans don't cover: host-side
    scheduling between the fused calls.
    """
    reqs = {}
    for meta, spans in bundles:
        for s in spans:
            if s.get("cat") != "serve":
                continue
            acc = reqs.setdefault(s["trace"], {
                "queue_s": 0.0, "prefill_s": 0.0, "decode_s": 0.0,
                "n_decode_calls": 0, "spec_accepted": 0,
                "prefix_hit": None, "total_s": None})
            name = s.get("name")
            dur = max(s["t1"] - s["t0"], 0.0)
            if name == "queue":
                acc["queue_s"] += dur
            elif name == "prefill":
                acc["prefill_s"] += dur
                acc["prefix_hit"] = bool(s.get("hit"))
            elif name == "decode":
                acc["decode_s"] += dur
                acc["n_decode_calls"] += 1
                acc["spec_accepted"] += int(s.get("accepted", 0))
            elif name == "request":
                acc["total_s"] = dur
                acc["tokens"] = s.get("tokens")
                acc["replica"] = s.get("replica")
    out = {}
    for trace, acc in reqs.items():
        if acc["total_s"] is None:
            continue                          # still in flight at flush
        acc["gap_s"] = max(acc["total_s"] - acc["queue_s"]
                           - acc["prefill_s"] - acc["decode_s"], 0.0)
        out[trace] = {k: (round(v, 9) if isinstance(v, float) else v)
                      for k, v in acc.items()}
    return out


def train_summary(bundles):
    steps, probes, total = 0, 0, 0.0
    for meta, spans in bundles:
        for s in spans:
            if s.get("cat") != "train":
                continue
            if s.get("name") == "train_step":
                steps += 1
                total += max(s["t1"] - s["t0"], 0.0)
            elif s.get("name") == "consensus_probe":
                probes += 1
    return {"steps": steps,
            "step_mean_s": round(total / steps, 9) if steps else None,
            "probes": probes}


def window_bounds(since=None, last=None, now=None):
    """``--since <wall-ts>`` / ``--last <secs>`` -> one lower wall-clock
    bound (None = keep everything; both given: later bound wins)."""
    if since is None and last is None:
        return None
    bounds = []
    if since is not None:
        bounds.append(float(since))
    if last is not None:
        if last <= 0:
            raise ValueError(f"--last must be > 0 seconds, got {last}")
        bounds.append((time.time() if now is None else float(now))
                      - float(last))
    return max(bounds)


def filter_bundles(bundles, cut):
    """Drop spans that *ended* before wall time ``cut`` (a span still
    running into the window counts: its tail is inside)."""
    if cut is None:
        return bundles
    return [(meta,
             [s for s in spans if _wall(meta, s["t1"]) >= cut])
            for meta, spans in bundles]


def report_from_files(paths, since=None, last=None):
    notes = []
    cut = window_bounds(since, last)
    bundles = [load_bundle(p, notes=notes) for p in paths]
    if cut is not None:
        before = sum(len(s) for _, s in bundles)
        bundles = filter_bundles(bundles, cut)
        dropped = before - sum(len(s) for _, s in bundles)
        if dropped:
            notes.append(f"window filter dropped {dropped} span(s) "
                         f"ending before {cut:.3f}")
    reqs = critical_path(bundles)
    table = sorted(
        ([t, v["total_s"], v["queue_s"], v["prefill_s"], v["decode_s"],
          v["gap_s"]] for t, v in reqs.items()),
        key=lambda row: -row[1])
    doc = {
        "ok": True,
        "schema": SCHEMA,
        "n_ranks": len(bundles),
        "ranks": sorted(m.get("rank", 0) for m, _ in bundles),
        "n_spans": sum(len(s) for _, s in bundles),
        "dropped": sum(m.get("dropped", 0) for m, _ in bundles),
        "requests": reqs,
        "critical_path": table,
        "train": train_summary(bundles),
    }
    if cut is not None:
        doc["window"] = {"since_ts": cut}
    if notes:
        doc["notes"] = notes
    return doc, bundles


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("bundles", nargs="*",
                    help="per-rank *.trace.jsonl bundles")
    ap.add_argument("--dir", default=None,
                    help="glob <dir>/*.trace.jsonl in addition to bundles")
    ap.add_argument("--out", default=None, help="write the report JSON here")
    ap.add_argument("--chrome", default=None,
                    help="write a chrome://tracing file here")
    ap.add_argument("--since", type=float, default=None, metavar="WALL_TS",
                    help="only report spans ending at/after this wall-clock "
                         "unix timestamp (slice a long-run artifact without "
                         "pre-splitting the JSONL)")
    ap.add_argument("--last", type=float, default=None, metavar="SECS",
                    help="only report spans from the trailing SECS seconds "
                         "(combines with --since: later bound wins)")
    args = ap.parse_args()
    paths = list(args.bundles)
    if args.dir:
        paths += sorted(glob.glob(os.path.join(args.dir, "*.trace.jsonl")))
    if not paths:
        print(json.dumps({"ok": False, "error": "no bundles given"}))
        sys.exit(1)
    try:
        doc, bundles = report_from_files(paths, since=args.since,
                                         last=args.last)
    except (OSError, ValueError) as e:
        doc = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
        bundles = None
    if args.chrome and bundles is not None:
        os.makedirs(os.path.dirname(args.chrome) or ".", exist_ok=True)
        with open(args.chrome, "w") as f:
            json.dump(chrome_trace(bundles), f)
        doc["chrome"] = args.chrome
    print(json.dumps(doc))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
    sys.exit(0 if doc.get("ok") else 1)


if __name__ == "__main__":
    main()
